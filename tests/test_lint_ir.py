"""graftlint IR pass (lint.ir + rules_ir) — the GL011-GL015 jaxpr gate.

Contracts under test:
  * the REAL tree is clean: the full entry matrix traces and produces
    zero IR findings through the actual CLI gate
    (``python -m lightgbm_tpu.lint --ir``) within the 30 s CPU budget;
  * mutation battery on copies of the REAL modules, each traced and
    audited through the same CLI: a raw psum spliced into the grower's
    smaller-child election (spelled so the GL007 AST pass CANNOT see
    it) is caught by exactly GL011; dropping the dtype pin on
    quantize_gradients' stochastic-rounding uniforms is caught by
    exactly GL012 (x64-invariance arm); stripping donate_argnums off
    the boosting score update is caught by exactly GL013; inflating a
    seg-kernel VMEM scratch block 16x past the v5e per-core arena is
    caught by exactly GL014;
  * IR findings round-trip through write_baseline/load_baseline on the
    (rule, path, ident) key, and the stale contract is full-matrix
    scoped: an IR baseline entry is exempt from stale detection when
    the IR pass is off or scoped down, and fails the run the moment a
    full matrix run shows it no longer fires;
  * the GL013 day-one triage holds at runtime: the donated score-update
    entry compiles exactly once across repeated same-shape calls
    (zero retrace delta).

The mutated copies must be IMPORTED to trace (unlike the pure-ast
battery in test_lint.py), so each mutation runs the CLI in a fresh
interpreter with cwd at the copy — the copy shadows the installed tree
on sys.path and PKG_ROOT resolves inside it.
"""

import json
import re
import shutil
import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import pytest

from lightgbm_tpu.lint import (
    Finding,
    load_baseline,
    run_lint,
    write_baseline,
)
from lightgbm_tpu.lint.core import IR_RULE_CODES

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "lightgbm_tpu"


# ----------------------------------------------------------------- helpers
def _tree_copy(tmp_path):
    """Copy the real package (plus the committed baseline, so the AST
    pass stays fully baselined on the copy) into tmp and return its
    root."""
    root = tmp_path / "tree"
    shutil.copytree(
        PKG,
        root / "lightgbm_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    shutil.copy(REPO / "lint_baseline.json", root / "lint_baseline.json")
    return root


def _mutate(root, rel, old, new):
    p = root / "lightgbm_tpu" / rel
    src = p.read_text()
    assert old in src, f"mutation target vanished from {rel}: {old!r}"
    p.write_text(src.replace(old, new, 1))


def _run_cli(root, *args, timeout=240):
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint", *args],
        cwd=root,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    return proc


def _ir_new(proc):
    """IR-rule findings from a --json CLI run."""
    data = json.loads(proc.stdout)
    return [f for f in data["new"] if f["rule"] in IR_RULE_CODES], data


# ================================================================ the gate
def test_real_tree_ir_clean_through_cli_under_budget():
    """The committed tree traces the FULL entry matrix and is IR-clean
    through the exact command tools/run_tests.sh gates on, inside the
    30 s CPU budget."""
    proc = _run_cli(REPO, "--ir", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    ir_new, data = _ir_new(proc)
    assert ir_new == []
    assert data["stale"] == []
    assert data["cpu_s"] < 30.0
    # the IR pass actually ran: trace + per-rule timings are reported
    assert "ir_trace" in data["rule_timings_s"]
    for code in sorted(IR_RULE_CODES):
        assert code in data["rule_timings_s"]


# ======================================================== mutation battery
# Each mutation re-seeds a known bug shape into a copy of the REAL module
# and must be caught by exactly the intended IR rule when the copy is
# traced through the CLI.

# the smaller-child election psum in the sharded grow loop — a unique
# anchor in ops/grower.py (see test_lint.py for the AST-side anchors)
_PSUM_SITE = """nleft_g = timed_psum(
                    nleft, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                )"""
# spelled via getattr so the GL007 AST raw-collective check CANNOT
# resolve the callee: only the traced jaxpr shows the psum eqn, which is
# exactly the blind spot GL011 exists to close
_PSUM_RAW = 'nleft_g = getattr(lax, "ps" + "um")(nleft, p.axis_name)'


def test_mutation_raw_psum_is_caught_by_gl011_only(tmp_path):
    root = _tree_copy(tmp_path)
    _mutate(root, "ops/grower.py", _PSUM_SITE, _PSUM_RAW)
    proc = _run_cli(
        root, "--ir", "--ir-entries", "grow/data8", "--json"
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    ir_new, _ = _ir_new(proc)
    assert len(ir_new) == 1
    f = ir_new[0]
    assert f["rule"] == "GL011"
    assert f["ident"].startswith("unsanctioned:psum:")
    assert f["path"] == "lightgbm_tpu/ops/grower.py"


_DTYPE_PIN = "rg = jax.random.uniform(kg, grad.shape, dtype=jnp.float32)"
_DTYPE_UNPINNED = "rg = jax.random.uniform(kg, grad.shape)"


def test_mutation_unpinned_dtype_is_caught_by_gl012_only(tmp_path):
    """Dropping the dtype pin leaves the default trace identical (f32)
    but widens the whole rounding chain to f64 the moment enable_x64
    flips on — the x64-invariance arm catches it."""
    root = _tree_copy(tmp_path)
    _mutate(root, "ops/quantize.py", _DTYPE_PIN, _DTYPE_UNPINNED)
    proc = _run_cli(
        root, "--ir", "--ir-entries", "quant/quantize_gradients", "--json"
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    ir_new, _ = _ir_new(proc)
    assert len(ir_new) == 1
    f = ir_new[0]
    assert f["rule"] == "GL012"
    assert f["ident"] == "quant/quantize_gradients:x64"
    assert f["path"] == "lightgbm_tpu/ops/quantize.py"


_DONATED_DECOR = (
    "@functools.partial(instrumented_jit, donate_argnums=(0,))\n"
    "def _apply_tree_score("
)
_UNDONATED_DECOR = "@instrumented_jit\ndef _apply_tree_score("


def test_mutation_dropped_donation_is_caught_by_gl013_only(tmp_path):
    """Stripping donate_argnums off the per-iteration score update is
    caught with the wasted-bytes accounting, and --format=github
    renders the finding as a workflow annotation."""
    root = _tree_copy(tmp_path)
    _mutate(root, "boosting/gbdt.py", _DONATED_DECOR, _UNDONATED_DECOR)
    proc = _run_cli(
        root,
        "--ir",
        "--ir-entries",
        "boost/score_update",
        "--format=github",
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    annotations = [
        l for l in proc.stdout.splitlines() if l.startswith("::error")
    ]
    assert len(annotations) == 1
    assert re.match(
        r"::error file=lightgbm_tpu/boosting/gbdt\.py,line=\d+::"
        r"GL013 entry 'boost/score_update' rebinds carried state "
        r"'score'",
        annotations[0],
    ), annotations[0]


_SEG_TILE = "TILE = 512  # rows per DMA tile in seg_hist"
_SEG_TILE_BLOWN = "TILE = 8192  # rows per DMA tile in seg_hist"


def test_mutation_vmem_blowout_is_caught_by_gl014_only(tmp_path):
    """A 16x DMA-tile inflation keeps the kernel self-consistent (TILE
    is used symbolically throughout) but pushes the static working set
    (~21 MB of onehot/staging scratch) past the 16 MiB v5e arena — and
    the caller-side seg_vmem_ok guard never sees a direct kernel call,
    which is exactly why GL014 audits the traced pallas_call itself."""
    root = _tree_copy(tmp_path)
    _mutate(root, "ops/pallas/seg.py", _SEG_TILE, _SEG_TILE_BLOWN)
    proc = _run_cli(
        root, "--ir", "--ir-entries", "pallas/seg_hist_batch", "--json"
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    ir_new, _ = _ir_new(proc)
    assert len(ir_new) == 1
    f = ir_new[0]
    assert f["rule"] == "GL014"
    assert f["ident"].startswith("vmem:")
    assert f["path"] == "lightgbm_tpu/ops/pallas/seg.py"


# ================================================= baseline round-trip/stale
def test_ir_findings_round_trip_through_baseline(tmp_path):
    f = Finding(
        rule="GL013",
        path="lightgbm_tpu/boosting/gbdt.py",
        line=63,
        ident="boost/score_update:score",
        message="synthetic",
    )
    path = tmp_path / "baseline.json"
    write_baseline(path, [f])
    entries = load_baseline(path)
    assert [(e["rule"], e["path"], e["ident"]) for e in entries] == [
        (f.rule, f.path, f.ident)
    ]


def _baseline_plus_ir_entry(tmp_path):
    """The committed baseline plus one IR entry that no longer fires
    (the donation IS wired, so boost/score_update:score is satisfied)."""
    entries = load_baseline(REPO / "lint_baseline.json")
    entries.append(
        {
            "rule": "GL013",
            "path": "lightgbm_tpu/boosting/gbdt.py",
            "ident": "boost/score_update:score",
            "justification": "synthetic stale entry for the test",
        }
    )
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "entries": entries}))
    return path


def test_ir_baseline_entry_exempt_from_stale_when_ir_off(tmp_path):
    res = run_lint(PKG, baseline=_baseline_plus_ir_entry(tmp_path))
    assert res.stale == []
    assert res.ok


def test_ir_baseline_entry_exempt_when_matrix_scoped_down(tmp_path):
    res = run_lint(
        PKG,
        baseline=_baseline_plus_ir_entry(tmp_path),
        ir=True,
        ir_entry_filter=["quant/"],
    )
    assert res.stale == []
    assert res.ok


def test_ir_baseline_entry_goes_stale_on_full_matrix_run(tmp_path):
    res = run_lint(
        PKG, baseline=_baseline_plus_ir_entry(tmp_path), ir=True
    )
    assert [
        (e["rule"], e["ident"]) for e in res.stale
    ] == [("GL013", "boost/score_update:score")]
    assert not res.ok


# ====================================================== GL013 runtime proof
def test_donated_score_update_traces_once():
    """The donated score-update entry keeps a zero retrace delta across
    repeated same-shape calls (the satellite's byte-identity claim is
    covered by the golden model dumps; this pins the compile count)."""
    from lightgbm_tpu.boosting.gbdt import _apply_tree_score
    from lightgbm_tpu.obs.jit import compile_counts_by_label

    score = jnp.zeros((1, 32), jnp.float32)
    leaf_value = jnp.arange(7, dtype=jnp.float32)
    leaf_id = jnp.zeros((32,), jnp.int32)
    before = compile_counts_by_label().get("_apply_tree_score", 0)
    s1 = _apply_tree_score(score, leaf_value, leaf_id, jnp.int32(0))
    s2 = _apply_tree_score(s1, leaf_value, leaf_id, jnp.int32(0))
    after = compile_counts_by_label().get("_apply_tree_score", 0)
    assert after - before == 1  # donation does not perturb retrace count
    assert s2.shape == score.shape
    assert float(s2[0, 0]) == 0.0  # leaf 0 value added twice, still 0
