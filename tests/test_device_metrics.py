"""Device-side metric evaluation matches the host (NumPy) path.

The booster prefers Metric.eval_device (score stays in HBM; only the scalar
crosses) and falls back to the host path per metric — VERDICT weak #4."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.config import Config  # noqa: E402
from lightgbm_tpu.metrics import create_metric  # noqa: E402


@pytest.mark.parametrize(
    "name,label_kind",
    [
        ("l2", "reg"),
        ("rmse", "reg"),
        ("l1", "reg"),
        ("quantile", "reg"),
        ("huber", "reg"),
        ("fair", "reg"),
        ("mape", "reg"),
        ("binary_logloss", "binary"),
        ("binary_error", "binary"),
        ("auc", "binary"),
    ],
)
@pytest.mark.parametrize("weighted", [False, True])
def test_device_matches_host(name, label_kind, weighted):
    import zlib
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    n = 3000
    score = rng.normal(size=(1, n))
    if label_kind == "binary":
        label = (rng.random(n) < 0.4).astype(np.float64)
    else:
        label = rng.normal(size=n) + 1.5
    weight = rng.random(n) + 0.5 if weighted else None
    cfg = Config.from_params({})
    m = create_metric(name, cfg)
    m.init(label, weight, None)

    class _Obj:  # identity for reg; sigmoid for binary-prob metrics
        name = "binary" if label_kind == "binary" else "regression"

        def convert_output(self, raw):
            if label_kind == "binary":
                return 1.0 / (1.0 + jnp.exp(-raw))
            return raw

    obj = _Obj()
    host = dict(m.eval(np.asarray(score), obj))
    dev = dict(m.eval_device(jnp.asarray(score, jnp.float32), obj))
    for k in host:
        assert host[k] == pytest.approx(dev[k], rel=2e-4, abs=1e-5), (
            k, host[k], dev[k],
        )


def test_multi_logloss_device_matches_host():
    rng = np.random.default_rng(0)
    n, k = 2000, 4
    X = rng.normal(size=(n, 5))
    y = rng.integers(0, k, size=n)
    ev = {}
    b = lgb.train(
        {
            "objective": "multiclass",
            "num_class": k,
            "verbosity": -1,
            "metric": "multi_logloss",
            "num_leaves": 7,
        },
        lgb.Dataset(X, y),
        3,
        valid_sets=[lgb.Dataset(X, y)],
        valid_names=["t"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    # cross-check the recorded (device-path) value against host recompute
    probs = b.predict(X)
    want = float(-np.log(np.clip(probs[np.arange(n), y], 1e-15, None)).mean())
    assert ev["t"]["multi_logloss"][-1] == pytest.approx(want, rel=1e-3)
