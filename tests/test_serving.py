"""Serving plane (lightgbm_tpu.serving): batcher, registry, refresh, server.

Contracts under test:
  * micro-batched serving is BIT-IDENTICAL per request to calling
    ``Booster.predict`` directly — across the bucket ladder, remainder
    buckets, coalesced mixed-size batches, and the real-space walker
    (model_str round-trip, f64 suspect re-walk included);
  * after the load-time ladder warmup, NO request of any size compiles
    anything (``compile_counts_by_label`` stays flat);
  * two co-resident models keep distinct per-model executable scopes
    (``predict/stream/{id}@v{n}/...`` labels) — the satellite-1 regression;
  * hot-swap is atomic under concurrent load: every response matches one
    model version exactly, never a mix;
  * LRU eviction under a device-memory budget drops the least-recently
    used idle model;
  * the refresh loop's metric gate promotes/rejects and writes an atomic
    artifact that round-trips bit-identically;
  * the chaos drills (swap_under_load, kill_during_warmup) pass;
  * the HTTP front end serves /predict, /models, /healthz (with the
    serving block) and /metrics (with lgbtpu_serve_*).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.health import HealthWatchdog
from lightgbm_tpu.predict import LADDER_MIN, bucket_rows
from lightgbm_tpu.resilience import chaos
from lightgbm_tpu.serving import MicroBatcher, ModelRegistry, RefreshLoop


def _train(seed=0, n=600, f=8, rounds=5, objective="binary"):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    if objective == "binary":
        y = ((X @ w) > 0).astype(np.float64)
    else:
        y = X @ w + 0.1 * rng.normal(size=n)
    bst = lgb.train(
        {"objective": objective, "num_leaves": 15, "verbose": -1},
        lgb.Dataset(X, label=y),
        num_boost_round=rounds,
    )
    return bst, X, y


@pytest.fixture(scope="module")
def served():
    """One warmed server + references computed BEFORE serving starts
    (the registry re-scopes the booster's engine at load, so pre-serve
    predictions are the independent oracle)."""
    bst, X, _ = _train()
    rng = np.random.default_rng(42)
    queries = {
        n: rng.normal(size=(n, X.shape[1]))
        for n in (1, 3, 17, LADDER_MIN, LADDER_MIN + 1, 512, 700)
    }
    refs = {n: bst.predict(q) for n, q in queries.items()}
    server = lgb.serve(bst, deadline_ms=3.0, max_batch=512, port=-1)
    yield server, bst, queries, refs
    server.stop()


# ---------------------------------------------------------------- parity


def test_microbatch_parity_bit_identical(served):
    server, _, queries, refs = served
    for n, q in queries.items():
        got = server.predict(q, timeout=30.0)
        assert got.shape == refs[n].shape
        assert np.array_equal(got, refs[n]), f"rows={n} not bit-identical"


def test_concurrent_mixed_sizes_parity(served):
    server, _, queries, refs = served
    futs = [
        (n, server.predict_async(q))
        for n, q in list(queries.items()) * 4
    ]
    for n, f in futs:
        resp = f.result(timeout=30.0)
        assert np.array_equal(resp.values, refs[n]), f"rows={n} mixed up"
        assert resp.info["model_id"] == "default"


def test_real_space_parity_model_str_roundtrip():
    """The real-space walker (no train-set bins, f64 suspect re-walk)
    must serve bit-identically too."""
    bst, X, _ = _train(seed=7, objective="regression")
    loaded = lgb.Booster(model_str=bst.model_to_string())
    rng = np.random.default_rng(11)
    Xq = rng.normal(size=(301, X.shape[1]))
    ref = loaded.predict(Xq)
    with lgb.serve(loaded, deadline_ms=2.0, max_batch=512, port=0) as srv:
        assert np.array_equal(srv.predict(Xq, timeout=30.0), ref)


# ------------------------------------------------------------- batcher


def _stub_dispatch(log):
    def dispatch(plans):
        log.append([(m.shape, live) for m, live in plans])
        outs = [m[:live].sum(axis=1) for m, live in plans]
        return np.concatenate(outs), {"model_id": "stub"}

    return dispatch


def test_batcher_plans_are_ladder_buckets():
    log = []
    b = MicroBatcher(_stub_dispatch(log), deadline_ms=20.0, max_batch=512)
    try:
        X = np.arange(700.0 * 4).reshape(700, 4)
        got = b.submit(X).result(timeout=30.0)
        assert np.array_equal(got.values, X.sum(axis=1))
    finally:
        b.stop()
    (plans,) = log
    # 700 rows, chunk 512: one full 512 plan + a 188-live remainder
    # padded to its 256 bucket
    assert plans == [((512, 4), 512), ((256, 4), 188)]
    for (rows, _), live in plans:
        assert rows == bucket_rows(live, 512)


def test_batcher_deadline_vs_full_flush_and_carry():
    log = []
    b = MicroBatcher(_stub_dispatch(log), deadline_ms=200.0, max_batch=256)
    try:
        # lone small request: nothing else arrives -> deadline flush
        r = b.submit(np.ones((8, 3))).result(timeout=30.0)
        assert r.values.shape == (8,)
        assert b.counters["deadline_flush"] == 1
        # 200 + 100 rows: the second overflows 256, so the first batch
        # flushes FULL and the overflow is carried (FIFO) to the next
        f1 = b.submit(np.full((200, 3), 2.0))
        f2 = b.submit(np.full((100, 3), 3.0))
        assert np.array_equal(f1.result(timeout=30.0).values, np.full(200, 6.0))
        assert np.array_equal(f2.result(timeout=30.0).values, np.full(100, 9.0))
        stats = b.stats()
        assert stats["full_flush"] >= 1
        assert stats["requests"] == 3
    finally:
        b.stop()


def test_batcher_rejects_bad_input_and_stop():
    b = MicroBatcher(_stub_dispatch([]), deadline_ms=5.0, max_batch=64)
    with pytest.raises(ValueError):
        b.submit(np.zeros((0, 3)))
    b.stop()
    with pytest.raises(RuntimeError):
        b.submit(np.zeros((1, 3)))


# -------------------------------------------------- compile discipline


def test_zero_recompiles_after_warmup(served):
    server, _, queries, _ = served
    # one pass so every size has been seen at least once post-warmup
    for q in queries.values():
        server.predict(q, timeout=30.0)
    before = dict(lgb.compile_counts_by_label())
    for _ in range(3):
        for q in queries.values():
            server.predict(q, timeout=30.0)
    after = dict(lgb.compile_counts_by_label())
    assert after == before, {
        k: (before.get(k, 0), v)
        for k, v in after.items()
        if before.get(k, 0) != v
    }


def test_two_models_get_distinct_exec_scopes():
    """Satellite-1 regression: co-resident models must compile under
    their own ``predict/stream/{scope}/...`` labels, not shared keys."""
    b1, X, _ = _train(seed=1)
    b2, _, _ = _train(seed=2)
    with lgb.serve(
        {"alpha": b1, "beta": b2}, deadline_ms=2.0, max_batch=256, port=0
    ) as srv:
        rng = np.random.default_rng(5)
        Xq = rng.normal(size=(33, X.shape[1]))
        pa = srv.predict(Xq, model_id="alpha", timeout=30.0)
        pb = srv.predict(Xq, model_id="beta", timeout=30.0)
        assert not np.array_equal(pa, pb)
        labels = lgb.compile_counts_by_label()
        for scope in ("alpha@v1", "beta@v1"):
            assert any(
                lbl.startswith(f"predict/stream/{scope}/") for lbl in labels
            ), f"no scoped exec labels for {scope}: {sorted(labels)}"


# ------------------------------------------------------------ hot-swap


def test_hot_swap_atomicity_under_concurrent_load():
    b1, X, _ = _train(seed=3, objective="regression")
    b2, _, _ = _train(seed=4, objective="regression")
    rng = np.random.default_rng(9)
    Xq = rng.normal(size=(40, X.shape[1]))
    p1, p2 = b1.predict(Xq), b2.predict(Xq)
    assert not np.array_equal(p1, p2)
    with lgb.serve(b1, deadline_ms=1.0, max_batch=256, port=0) as srv:
        futures, stop = [], threading.Event()

        def client():
            # paced + bounded: the swap's warmup takes seconds, and an
            # unthrottled submit loop would bury the worker under an
            # unbounded backlog of futures
            for _ in range(300):
                if stop.is_set():
                    break
                futures.append(srv.predict_async(Xq))
                time.sleep(0.002)

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        info = srv.swap("default", b2)
        stop.set()
        for t in threads:
            t.join()
        assert info["version"] == 2
        seen = {1: 0, 2: 0}
        for f in futures:
            resp = f.result(timeout=30.0)
            if np.array_equal(resp.values, p1):
                assert resp.info["version"] == 1
            elif np.array_equal(resp.values, p2):
                assert resp.info["version"] == 2
            else:
                raise AssertionError("response mixes model versions")
            seen[resp.info["version"]] += 1
        # post-swap requests must serve v2 exactly
        assert np.array_equal(srv.predict(Xq, timeout=30.0), p2)
        assert srv.serving_snapshot()["models"][0]["version"] == 2


# ------------------------------------------------------------ registry


def test_registry_lru_eviction_under_budget():
    ba, _, _ = _train(seed=5, rounds=3)
    bb, _, _ = _train(seed=6, rounds=3)
    probe = ModelRegistry(chunk=256)
    entry = probe.load("probe", lgb.Booster(model_str=ba.model_to_string()))
    per_model = entry.device_bytes
    probe.close()
    assert per_model > 0
    # budget fits ~one model: loading the second must evict the first
    reg = ModelRegistry(
        chunk=256, memory_budget_bytes=int(per_model * 1.5)
    )
    try:
        reg.load("a", lgb.Booster(model_str=ba.model_to_string()))
        reg.load("b", lgb.Booster(model_str=bb.model_to_string()))
        ids = {m["model_id"] for m in reg.models()}
        assert ids == {"b"}, ids
        with pytest.raises(KeyError):
            reg.booster("a")
        assert reg.resident_bytes() <= int(per_model * 1.5)
    finally:
        reg.close()


def test_registry_load_twice_rejected():
    bst, _, _ = _train(seed=8, rounds=2)
    reg = ModelRegistry(chunk=256)
    try:
        reg.load("m", bst, warm=False)
        with pytest.raises(ValueError):
            reg.load("m", bst, warm=False)
    finally:
        reg.close()


# ------------------------------------------------------------- refresh


def test_refresh_gate_promotes_and_writes_atomic_artifact(tmp_path):
    bst, X, y = _train(seed=10, objective="regression")
    path = str(tmp_path / "refreshed.txt")
    with lgb.serve(bst, deadline_ms=2.0, max_batch=256, port=0) as srv:
        loop = srv.refresh_loop(
            min_rows=64, metric="l2", tolerance=1e9, save_path=path
        )
        loop.observe(X[:300], y[:300])
        report = loop.run_once()
        assert report["promoted"], report
        assert report["version"] == 2
        assert report["artifact"] == path
        promoted = srv.registry.booster("default")
        served = srv.predict(X[:90], timeout=30.0)
    # the artifact round-trips bit-identically to the promoted model
    reloaded = lgb.Booster(model_file=path)
    assert np.array_equal(reloaded.predict(X[:90]), promoted.predict(X[:90]))
    assert np.array_equal(served, promoted.predict(X[:90]))


def test_refresh_gate_rejects_worse_candidate(tmp_path):
    bst, X, y = _train(seed=12, objective="regression")
    with lgb.serve(bst, deadline_ms=2.0, max_batch=256, port=0) as srv:
        loop = srv.refresh_loop(min_rows=64, metric="l2", tolerance=-1e9)
        loop.observe(X[:200], y[:200])
        report = loop.run_once()
        assert not report["promoted"]
        assert loop.rejections == 1
        assert srv.serving_snapshot()["models"][0]["version"] == 1
    # insufficient traffic short-circuits without touching the model
    loop2 = RefreshLoop(srv.registry, "default", min_rows=10**6)
    assert loop2.run_once()["reason"] == "insufficient_rows"


# ------------------------------------------------------------ watchdog


def test_watchdog_serving_rule():
    wd = HealthWatchdog(deadline_miss_ceiling=0.25, deadline_miss_min_requests=16)
    quiet = wd.observe_serving(
        {"iter": 1, "deadline_miss_rate": 0.9, "requests": 4}
    )
    assert quiet == []  # below the min-requests floor: no alert
    alerts = wd.observe_serving(
        {"iter": 2, "deadline_miss_rate": 0.9, "requests": 64}
    )
    assert [a["rule"] for a in alerts] == ["serve_deadline"]
    ok = wd.observe_serving(
        {"iter": 3, "deadline_miss_rate": 0.0, "requests": 64}
    )
    assert ok == []


# --------------------------------------------------------------- chaos


def test_chaos_swap_under_load_drill(tmp_path):
    dump = chaos.swap_under_load_drill(str(tmp_path))
    assert dump


def test_chaos_kill_during_warmup_drill(tmp_path):
    dump = chaos.kill_during_warmup_drill(str(tmp_path))
    assert dump


# ----------------------------------------------------------------- http


def test_http_front_end(served):
    server, _, queries, refs = served
    assert server.url.startswith("http://127.0.0.1:")
    Xq = queries[17]
    req = urllib.request.Request(
        server.url + "/predict",
        data=json.dumps({"rows": Xq.tolist()}).encode(),
        headers={"Content-Type": "application/json"},
    )
    doc = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert np.array_equal(np.asarray(doc["predictions"]), refs[17])
    assert doc["model_id"] == "default" and doc["version"] >= 1

    models = json.loads(
        urllib.request.urlopen(server.url + "/models", timeout=10).read()
    )
    assert models["models"][0]["model_id"] == "default"

    hz = json.loads(
        urllib.request.urlopen(server.url + "/healthz", timeout=10).read()
    )
    assert "serving" in hz
    assert hz["serving"]["models"][0]["model_id"] == "default"
    assert "default" in hz["serving"]["batchers"]

    text = (
        urllib.request.urlopen(server.url + "/metrics", timeout=10)
        .read()
        .decode()
    )
    for name in (
        "lgbtpu_serve_p50_ms",
        "lgbtpu_serve_p99_ms",
        "lgbtpu_serve_batch_fill",
        "lgbtpu_serve_deadline_miss_rate",
        "lgbtpu_serve_requests_total",
    ):
        assert any(
            line.startswith(name) for line in text.splitlines()
        ), f"{name} missing from /metrics"


def test_http_bad_request_and_unknown_model(served):
    server, _, _, _ = served

    def post(payload):
        req = urllib.request.Request(
            server.url + "/predict", data=payload,
            headers={"Content-Type": "application/json"},
        )
        try:
            return urllib.request.urlopen(req, timeout=10).status
        except urllib.error.HTTPError as e:
            return e.code

    assert post(b"not json") == 400
    assert post(json.dumps({"rows": [[0.0] * 8], "model": "nope"}).encode()) == 404
