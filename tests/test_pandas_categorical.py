"""pandas categorical (string) columns: stable code recording + predict
remap + model-file persistence.

Reference analog: python-package/lightgbm/basic.py ``_data_from_pandas`` /
``pandas_categorical`` (category orders recorded at train, appended to the
model file, and used to remap predict-time frames)."""

import numpy as np
import pytest

pd = pytest.importorskip("pandas")

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def cat_model():
    rng = np.random.default_rng(7)
    n = 600
    df = pd.DataFrame(
        {
            "a": rng.normal(size=n),
            "c": pd.Categorical(rng.choice(["x", "y", "z"], n)),
        }
    )
    y = df["a"].to_numpy() + (df["c"] == "y") * 2.0
    ds = lgb.Dataset(df, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbose": -1},
        ds,
        num_boost_round=20,
    )
    return df, y, ds, bst


def test_train_learns_category(cat_model):
    df, y, _, bst = cat_model
    p = bst.predict(df)
    assert np.sqrt(np.mean((p - y) ** 2)) < 0.5


def test_predict_reordered_categories_identical(cat_model):
    df, _, _, bst = cat_model
    p = bst.predict(df)
    df2 = df.copy()
    df2["c"] = df2["c"].cat.reorder_categories(["z", "x", "y"])
    assert np.array_equal(p, bst.predict(df2))


def test_predict_object_dtype_identical(cat_model):
    df, _, _, bst = cat_model
    df5 = df.copy()
    df5["c"] = df["c"].astype(str)
    assert np.array_equal(bst.predict(df), bst.predict(df5))


def test_unseen_category_routes_like_missing(cat_model):
    df, _, _, bst = cat_model
    n = len(df)
    df3 = df.copy()
    df3["c"] = pd.Categorical(
        np.where(np.arange(n) % 7 == 0, "w", df["c"].astype(str))
    )
    p3 = bst.predict(df3)
    assert np.isfinite(p3).all()
    # rows with seen categories are unaffected
    keep = np.arange(n) % 7 != 0
    assert np.array_equal(bst.predict(df)[keep], p3[keep])


def test_model_file_roundtrip_preserves_maps(cat_model, tmp_path):
    df, _, _, bst = cat_model
    f = str(tmp_path / "m.txt")
    bst.save_model(f)
    bst2 = lgb.Booster(model_file=f)
    # the file stores the reference's positional list-of-lists shape
    assert bst2.pandas_categorical == [["x", "y", "z"]]
    df2 = df.copy()
    df2["c"] = df2["c"].cat.reorder_categories(["z", "x", "y"])
    assert np.array_equal(bst.predict(df), bst2.predict(df2))


def test_valid_set_reuses_train_maps(cat_model):
    df, y, ds, _ = cat_model
    df2 = df.copy()
    df2["c"] = df2["c"].cat.reorder_categories(["z", "x", "y"])
    res = {}
    lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbose": -1},
        ds,
        num_boost_round=5,
        valid_sets=[
            lgb.Dataset(df, label=y, reference=ds),
            lgb.Dataset(df2, label=y, reference=ds),
        ],
        valid_names=["orig", "reordered"],
        callbacks=[lgb.record_evaluation(res)],
    )
    # identical rows (modulo category order) -> identical eval series
    assert res["orig"]["l2"] == res["reordered"]["l2"]


def test_numeric_categories_survive_model_file(tmp_path):
    """int-valued categoricals must round-trip as ints, not strings."""
    rng = np.random.default_rng(5)
    n = 400
    df = pd.DataFrame(
        {
            "a": rng.normal(size=n),
            "c": pd.Categorical(rng.choice([10, 20, 30], n)),
        }
    )
    y = df["a"].to_numpy() + (df["c"] == 20) * 2.0
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbose": -1},
        lgb.Dataset(df, label=y),
        num_boost_round=10,
    )
    p = bst.predict(df)
    f = str(tmp_path / "m.txt")
    bst.save_model(f)
    bst2 = lgb.Booster(model_file=f)
    assert bst2.pandas_categorical == [[10, 20, 30]]
    assert np.array_equal(p, bst2.predict(df))


def test_model_without_trailer_resets_maps(cat_model):
    df, _, _, bst = cat_model
    s = bst.model_to_string()
    bare = s[: s.index("pandas_categorical:")].rstrip() + "\n"
    bst2 = lgb.Booster(model_str=s)
    assert bst2.pandas_categorical
    bst2.model_from_string(bare)
    assert bst2.pandas_categorical is None


def test_reference_style_list_maps_predict():
    """A model file with the reference python package's list-of-lists
    pandas_categorical still remaps (zipped with the frame's categorical
    columns in order)."""
    rng = np.random.default_rng(3)
    n = 400
    df = pd.DataFrame(
        {
            "a": rng.normal(size=n),
            "c": pd.Categorical(rng.choice(["x", "y", "z"], n)),
        }
    )
    y = df["a"].to_numpy() + (df["c"] == "y") * 2.0
    ds = lgb.Dataset(df, label=y)
    bst = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbose": -1},
        ds,
        num_boost_round=10,
    )
    s = bst.model_to_string()
    # the trailer is written in the reference's list-of-lists shape (zipped
    # positionally with the frame's categorical columns) so reference-package
    # loads see categories, not NaNs; a {name: cats} dict form is still
    # accepted on load
    assert 'pandas_categorical:[["x", "y", "z"]]' in s
    bst2 = lgb.Booster(model_str=s)
    assert bst2.pandas_categorical == [["x", "y", "z"]]
    bst3 = lgb.Booster(
        model_str=s.replace(
            'pandas_categorical:[["x", "y", "z"]]',
            'pandas_categorical:{"c": ["x", "y", "z"]}',
        )
    )
    assert bst3.pandas_categorical == {"c": ["x", "y", "z"]}
    df2 = df.copy()
    df2["c"] = df2["c"].cat.reorder_categories(["y", "z", "x"])
    assert np.array_equal(bst.predict(df), bst2.predict(df2))


def test_subset_and_binary_keep_category_maps(cat_model, tmp_path):
    """Dataset.subset / save_binary+load carry the recorded category maps
    (a subset-trained booster must still remap predict frames)."""
    df, y, ds, _ = cat_model
    sub = ds.subset(np.arange(0, len(df), 2))
    b = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbose": -1}, sub, 5
    )
    assert b.pandas_categorical == {"c": ["x", "y", "z"]}
    f = str(tmp_path / "d.bin")
    ds.save_binary(f)
    d2 = lgb.Dataset(f)
    d2.construct()
    assert d2.pandas_categorical == {"c": ["x", "y", "z"]}
