"""Group-aware cross-validation for ranking objectives (reference:
engine.py:559 — folds split by whole queries so no query straddles folds)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.engine import _make_n_folds  # noqa: E402


def test_folds_keep_queries_whole():
    rng = np.random.default_rng(0)
    sizes = rng.integers(3, 9, size=30)
    n = int(sizes.sum())
    X = rng.normal(size=(n, 3))
    y = rng.random(n)
    d = lgb.Dataset(X, y, group=sizes)
    folds = list(
        _make_n_folds(d, 3, {}, seed=1, stratified=False, shuffle=True,
                      group_aware=True)
    )
    qb = np.concatenate([[0], np.cumsum(sizes)])
    starts = set(qb[:-1])
    all_test = []
    for train_idx, test_idx, tg, eg in folds:
        assert tg is not None and eg is not None
        assert tg.sum() == len(train_idx) and eg.sum() == len(test_idx)
        # each fold's test rows are a union of whole queries
        pos = 0
        for size in eg:
            seg = test_idx[pos : pos + size]
            assert seg[0] in starts
            assert np.array_equal(seg, np.arange(seg[0], seg[0] + size))
            pos += size
        all_test.append(test_idx)
    # folds partition the rows
    union = np.sort(np.concatenate(all_test))
    assert np.array_equal(union, np.arange(n))


def test_ranking_cv_end_to_end():
    rng = np.random.default_rng(3)
    nq, q = 45, 6
    X = rng.normal(size=(nq * q, 4))
    y = (X[:, 0] + 0.3 * rng.normal(size=nq * q) > 0.4).astype(float)
    res = lgb.cv(
        {
            "objective": "lambdarank",
            "verbosity": -1,
            "min_data_in_leaf": 2,
            "metric": "ndcg",
            "eval_at": [3],
        },
        lgb.Dataset(X, y, group=np.full(nq, q), free_raw_data=False),
        num_boost_round=4,
        nfold=3,
    )
    assert any("ndcg@3-mean" in k for k in res)
    vals = res[[k for k in res if "mean" in k][0]]
    assert len(vals) == 4 and np.isfinite(vals).all()
