"""Quantized-gradient training (reference: GradientDiscretizer,
src/treelearner/gradient_discretizer.cpp; config use_quantized_grad)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.ops.quantize import quantize_gradients  # noqa: E402


def test_quantize_grid_and_scales():
    rng = np.random.default_rng(0)
    g = rng.normal(size=512).astype(np.float32)
    h = np.abs(rng.normal(size=512)).astype(np.float32) + 0.1
    qg, qh, gs, hs = quantize_gradients(
        jnp.asarray(g), jnp.asarray(h), jax.random.PRNGKey(0),
        num_bins=4, stochastic=False,
    )
    qg, qh = np.asarray(qg), np.asarray(qh)
    g_scale, h_scale = float(gs), float(hs)
    assert g_scale == pytest.approx(np.abs(g).max() / 2)  # num_bins/2
    assert h_scale == pytest.approx(h.max() / 4)
    # every quantized value sits on the integer grid of its scale
    assert np.allclose(np.round(qg / g_scale), qg / g_scale, atol=1e-4)
    assert np.allclose(np.round(qh / h_scale), qh / h_scale, atol=1e-4)
    # deterministic rounding: |error| <= scale/2 (+ eps)
    assert np.abs(qg - g).max() <= g_scale * 0.5 + 1e-5
    assert np.abs(qh - h).max() <= h_scale * 0.5 + 1e-5


def test_stochastic_rounding_unbiased():
    g = jnp.full((20000,), 0.3, jnp.float32)
    h = jnp.ones((20000,), jnp.float32)
    qg, _, _, _ = quantize_gradients(
        g, h, jax.random.PRNGKey(1), num_bins=4, stochastic=True
    )
    # E[q] == g under stochastic rounding (reference stochastic_rounding)
    assert float(np.asarray(qg).mean()) == pytest.approx(0.3, rel=0.05)


@pytest.mark.parametrize("renew", [False, True])
def test_quantized_training_close_to_exact(renew):
    rng = np.random.default_rng(0)
    n = 3000
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] + rng.normal(scale=0.1, size=n)
    base = {
        "objective": "regression",
        "num_leaves": 31,
        "min_data_in_leaf": 10,
        "verbosity": -1,
    }
    exact = lgb.train(base, lgb.Dataset(X, y), 20)
    quant = lgb.train(
        {**base, "use_quantized_grad": True, "num_grad_quant_bins": 8,
         "quant_train_renew_leaf": renew},
        lgb.Dataset(X, y),
        20,
    )
    mse_exact = float(np.mean((exact.predict(X) - y) ** 2))
    mse_quant = float(np.mean((quant.predict(X) - y) ** 2))
    assert mse_quant < np.var(y) * 0.1  # genuinely learns
    assert mse_quant < mse_exact * 3.0 + 1e-3  # near the exact model
    if renew:
        # mechanism check: with renewal, the first tree's leaf values are
        # the TRUE-gradient optima -sum_g/(sum_h + l2) over each leaf
        # (RenewIntGradTreeOutput), not the quantized-gradient optima
        b1 = lgb.train(
            {**base, "use_quantized_grad": True, "num_grad_quant_bins": 8,
             "quant_train_renew_leaf": True, "learning_rate": 0.7,
             "boost_from_average": False},  # keep leaf values bias-free
            lgb.Dataset(X, y),
            1,
        )
        tree = b1.models_[0]
        leaves = b1.predict(X, pred_leaf=True)[:, 0]
        grad = -y  # L2 gradients at score 0
        for leaf in range(tree.num_leaves):
            sel = leaves == leaf
            if sel.sum() == 0:
                continue
            want = -grad[sel].sum() / (sel.sum() + 0.0) * 0.7  # lambda_l2=0
            assert tree.leaf_value[leaf] == pytest.approx(want, rel=1e-3), leaf


def test_quantized_binary():
    rng = np.random.default_rng(1)
    n = 2000
    X = rng.normal(size=(n, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    b = lgb.train(
        {
            "objective": "binary",
            "verbosity": -1,
            "use_quantized_grad": True,
            "quant_train_renew_leaf": True,
            "num_leaves": 15,
        },
        lgb.Dataset(X, y),
        15,
    )
    acc = ((b.predict(X) > 0.5) == y).mean()
    assert acc > 0.9
