"""Categorical split finding vs a NumPy oracle of the reference algorithm.

The oracle mirrors ``FindBestThresholdCategoricalInner``
(/root/reference/src/treelearner/feature_histogram.cpp:147-343): one-hot for
small cardinality, otherwise categories sorted by g/(h+cat_smooth) scanned
from both directions up to max_cat_threshold with cat_l2 regularization.
min_data_per_group is tested at 1 where the vectorized crossing-of-multiples
approximation is exact.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops.split import CatParams, best_split  # noqa: E402


def _np_leaf_gain(g, h, l1, l2):
    t = np.sign(g) * np.maximum(np.abs(g) - l1, 0.0)
    return (t * t) / (h + l2 + 1e-15)


def np_cat_best(hist, pg, ph, pc, num_bins, cp: CatParams, l1, l2,
                min_data, min_hess):
    """Oracle: best categorical split for ONE feature.

    Returns (raw_gain, left_bin_set) or (-inf, None)."""
    g, h, c = hist[:, 0], hist[:, 1], hist[:, 2]
    best = (-np.inf, None)

    def gain_of(lg, lh, lc, l2e):
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        if lc < min_data or rc < min_data or lh < min_hess or rh < min_hess:
            return -np.inf
        return _np_leaf_gain(lg, lh, l1, l2e) + _np_leaf_gain(rg, rh, l1, l2e)

    if num_bins <= cp.max_cat_to_onehot:
        for t in range(num_bins):
            gn = gain_of(g[t], h[t], c[t], l2)
            if gn > best[0]:
                best = (gn, {t})
    else:
        l2e = l2 + cp.cat_l2
        valid = [t for t in range(num_bins) if c[t] >= cp.cat_smooth]
        ctr = {t: g[t] / (h[t] + cp.cat_smooth) for t in valid}
        order = sorted(valid, key=lambda t: ctr[t])
        used = len(order)
        max_num_cat = min(cp.max_cat_threshold, (used + 1) // 2)
        for direction in (1, -1):
            seq = order if direction == 1 else order[::-1]
            lg = lh = lc = 0.0
            for i in range(min(used, max_num_cat)):
                t = seq[i]
                lg += g[t]
                lh += h[t]
                lc += c[t]
                if pc - lc < cp.min_data_per_group:
                    break
                gn = gain_of(lg, lh, lc, l2e)
                if gn > best[0]:
                    best = (gn, set(seq[: i + 1]))
    return best


def _problem(num_bins, f, n, seed):
    """Row-level categorical data -> per-feature histograms with a SHARED
    parent total (all features histogram the same rows)."""
    rng = np.random.default_rng(seed)
    b = 64
    bins = rng.integers(0, num_bins, size=(n, f))
    # per-category effects so subsets genuinely matter
    effect = rng.normal(scale=2.0, size=(f, num_bins))
    grad = effect[0][bins[:, 0]] + rng.normal(size=n)
    hess = np.ones(n)
    hist = np.zeros((f, b, 3))
    for j in range(f):
        np.add.at(hist[j, :, 0], bins[:, j], grad)
        np.add.at(hist[j, :, 1], bins[:, j], hess)
        np.add.at(hist[j, :, 2], bins[:, j], 1.0)
    return hist, grad.sum(), hess.sum(), float(n)


@pytest.mark.parametrize(
    "num_bins,max_oh", [(3, 4), (12, 4), (40, 4), (12, 16)]
)
def test_categorical_matches_oracle(num_bins, max_oh):
    f, n = 5, 600
    hist, pg, ph, pc = _problem(num_bins, f, n, seed=num_bins * 7 + max_oh)
    cp = CatParams(
        max_cat_to_onehot=max_oh,
        max_cat_threshold=8,
        cat_l2=2.0,
        cat_smooth=3.0,
        min_data_per_group=1,
    )
    l1, l2, min_data, min_hess = 0.0, 1.0, 3, 1e-3

    per_feature = [
        np_cat_best(hist[j], pg, ph, pc, num_bins, cp, l1, l2, min_data, min_hess)
        for j in range(f)
    ]
    best_j = int(np.argmax([pf[0] for pf in per_feature]))
    oracle_gain, oracle_set = per_feature[best_j]
    oracle_improvement = oracle_gain - _np_leaf_gain(pg, ph, l1, l2)

    cand = best_split(
        jnp.asarray(hist, jnp.float32),
        jnp.float32(pg),
        jnp.float32(ph),
        jnp.float32(pc),
        jnp.full((f,), num_bins, jnp.int32),
        jnp.full((f,), -1, jnp.int32),
        jnp.ones((f,), bool),
        lambda_l1=l1,
        lambda_l2=l2,
        min_data_in_leaf=min_data,
        min_sum_hessian_in_leaf=min_hess,
        min_gain_to_split=0.0,
        is_cat=jnp.ones((f,), bool),
        cat_params=cp,
    )
    assert bool(cand.is_cat)
    assert float(cand.gain) == pytest.approx(oracle_improvement, rel=1e-4)
    assert int(cand.feature) == best_j
    got_set = set(np.nonzero(np.asarray(cand.cat_mask))[0].tolist())
    assert got_set == oracle_set
    # left stats match the subset sums
    np.testing.assert_allclose(
        float(cand.left_cnt),
        sum(hist[best_j, t, 2] for t in oracle_set),
        rtol=1e-5,
    )


def test_e2e_categorical_beats_frequency_rank():
    """End-to-end: a target keyed to an arbitrary category SUBSET (unrelated
    to frequency) is learnable — the frequency-rank-prefix model provably
    cannot isolate it with one split, the sorted-subset scan can."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(42)
    n, k = 4000, 12
    # frequencies deliberately uncorrelated with effect: odd cats are +1
    probs = rng.dirichlet(np.ones(k))
    cat = rng.choice(k, size=n, p=probs)
    y = np.where(cat % 2 == 1, 1.0, -1.0) + rng.normal(scale=0.05, size=n)
    X = cat.reshape(-1, 1).astype(np.float64)

    params = {
        "objective": "regression",
        "num_leaves": 2,
        "min_data_in_leaf": 5,
        "min_data_per_group": 1,
        "cat_smooth": 1.0,
        "max_cat_to_onehot": 1,  # force the sorted-subset path
        "learning_rate": 1.0,
        "verbosity": -1,
    }
    d = lgb.Dataset(X, y, categorical_feature=[0])
    bst = lgb.train(params, d, num_boost_round=1)
    tree = bst.models_[0]
    assert tree.num_leaves == 2
    assert tree.decision_type[0] & 1  # categorical split
    # one split must isolate the odd set: per-category predictions correct
    pred = bst.predict(np.arange(k, dtype=np.float64).reshape(-1, 1))
    base = y.mean()
    odd, even = pred[1::2].mean(), pred[0::2].mean()
    assert odd - even > 1.5, (odd, even)  # clean separation, not freq prefix


def test_e2e_categorical_roundtrip_and_consistency():
    """Trained cat model: device (bin-space) training scores == host predict,
    and model text round-trip preserves predictions exactly."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    n, k = 1500, 20
    cat = rng.integers(0, k, size=n)
    num = rng.normal(size=n)
    y = np.sin(cat * 1.7) + 0.5 * num + rng.normal(scale=0.1, size=n)
    X = np.column_stack([cat.astype(np.float64), num])
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "min_data_in_leaf": 5,
        "min_data_per_group": 1,
        "verbosity": -1,
        "metric": "l2",
    }
    d = lgb.Dataset(X, y, categorical_feature=[0])
    ev = {}
    bst = lgb.train(
        params, d, num_boost_round=10,
        valid_sets=[d], valid_names=["train"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    pred = bst.predict(X)
    # the device training score and the host prediction walk must agree
    final_l2 = ev["train"]["l2"][-1]
    assert float(np.mean((pred - y) ** 2)) == pytest.approx(final_l2, rel=1e-3)
    assert final_l2 < 0.25 * np.var(y)
    # text round-trip
    b2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(b2.predict(X), pred, rtol=1e-6, atol=1e-7)


def test_e2e_categorical_nan_goes_right():
    """NaN categorical values follow the prediction rule (right child) during
    training too — train/predict consistency with missing categoricals."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    n, k = 1200, 8
    cat = rng.integers(0, k, size=n).astype(np.float64)
    nan_rows = rng.random(n) < 0.15
    cat[nan_rows] = np.nan
    y = np.where(np.isnan(cat), 0.0, np.where(cat % 2 == 1, 1.0, -1.0))
    y = y + rng.normal(scale=0.05, size=n)
    X = cat.reshape(-1, 1)
    params = {
        "objective": "regression",
        "num_leaves": 8,
        "min_data_in_leaf": 5,
        "min_data_per_group": 1,
        "max_cat_to_onehot": 1,
        "verbosity": -1,
        "metric": "l2",
    }
    ev = {}
    bst = lgb.train(
        params, lgb.Dataset(X, y, categorical_feature=[0]), num_boost_round=8,
        valid_sets=[lgb.Dataset(X, y, categorical_feature=[0])],
        valid_names=["train"],
        callbacks=[lgb.record_evaluation(ev)],
    )
    pred = bst.predict(X)
    # bin-space (training) and real-space (predict) walks agree
    assert float(np.mean((pred - y) ** 2)) == pytest.approx(
        ev["train"]["l2"][-1], rel=1e-3
    )


def test_mixed_numeric_and_categorical():
    """A numeric feature with a clean threshold must win over a weak
    categorical, and vice versa — the combined argmax is coherent."""
    rng = np.random.default_rng(0)
    n, b = 400, 64
    # feature 0: numeric, perfectly splits at bin < 8
    nume = rng.integers(0, 16, size=n)
    grad = np.where(nume < 8, -1.0, 1.0) + 0.01 * rng.normal(size=n)
    # feature 1: categorical, weak effect
    catv = rng.integers(0, 10, size=n)
    hist = np.zeros((2, b, 3))
    np.add.at(hist[0, :, 0], nume, grad)
    np.add.at(hist[0, :, 1], nume, 1.0)
    np.add.at(hist[0, :, 2], nume, 1.0)
    np.add.at(hist[1, :, 0], catv, grad * 0.01)
    np.add.at(hist[1, :, 1], catv, 1.0)
    np.add.at(hist[1, :, 2], catv, 1.0)
    # NOTE: feature 1's histogram must use the same grad rows for a shared
    # parent; scale only feature 1's association, not its totals
    np.add.at(hist[1, :, 0], catv, grad * 0.99)  # totals now match feature 0
    cand = best_split(
        jnp.asarray(hist, jnp.float32),
        jnp.float32(grad.sum()),
        jnp.float32(n),
        jnp.float32(n),
        jnp.asarray([16, 10], jnp.int32),
        jnp.asarray([-1, -1], jnp.int32),
        jnp.ones((2,), bool),
        lambda_l1=0.0,
        lambda_l2=1.0,
        min_data_in_leaf=5,
        min_sum_hessian_in_leaf=1e-3,
        min_gain_to_split=0.0,
        is_cat=jnp.asarray([False, True]),
        cat_params=CatParams(min_data_per_group=1),
    )
    assert int(cand.feature) == 0
    assert not bool(cand.is_cat)
    assert float(cand.gain) > 0
    assert int(cand.bin) == 7
