"""Multi-process launcher: 2 coordinated CPU processes form a cluster and a
psum spans both (the reference's machine-list TCP Allreduce as
jax.distributed + collectives)."""

import subprocess
import sys
import textwrap

import pytest

pytest.importorskip("jax")


REPO_ROOT = str(__import__("pathlib").Path(__file__).resolve().parents[1])

WORKER_TMPL = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    from lightgbm_tpu.parallel import init_distributed

    init_distributed()
    assert jax.process_count() == 2, jax.process_count()
    nloc = jax.local_device_count()
    assert jax.device_count() == 2 * nloc
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    mesh = Mesh(np.array(jax.devices()), ("data",))
    # every process contributes its local shard; the psum spans processes
    local = np.full((nloc,), float(jax.process_index() + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local
    )
    total = jax.jit(
        jax.shard_map(
            lambda x: jax.lax.psum(x, "data"),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"),
        )
    )(arr)
    got = float(np.asarray(jax.device_get(total.addressable_shards[0].data))[0])
    want = float(nloc * 1 + nloc * 2)  # both processes' shards summed
    assert got == want, (got, want)
    print(f"proc {jax.process_index()} ok")
    """
)


def test_two_process_psum(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER_TMPL.replace("__REPO__", REPO_ROOT))
    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "lightgbm_tpu.parallel.launcher",
            "-n",
            "2",
            "--port",
            "29517",
            str(script),
        ],
        capture_output=True,
        text=True,
        timeout=220,
        cwd=REPO_ROOT,
    )
    assert r.returncode == 0, r.stdout + r.stderr


BINSYNC_TMPL = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import numpy as np
    from lightgbm_tpu.parallel import init_distributed

    init_distributed()
    rank = jax.process_index()
    # each process holds DIFFERENT local rows (pre-partitioned), so local
    # quantiles disagree unless the mappers are synced
    rng = np.random.default_rng(100 + rank)
    X = rng.normal(loc=rank * 3.0, size=(4000, 5))
    y = X[:, 0] + rng.normal(size=4000)
    import lightgbm_tpu as lgb

    ds = lgb.Dataset(X, y, params={"pre_partition": True, "max_bin": 63})
    ds.construct()
    # mappers must be identical on every process: print a digest the parent
    # compares across workers
    import hashlib

    h = hashlib.sha256()
    for m in ds.bin_mappers:
        h.update(np.asarray(m.bin_upper_bound).tobytes())
        h.update(bytes([m.num_bins & 0xFF, m.missing_type & 0xFF]))
    print(f"MAPPERHASH {h.hexdigest()}")
    """
)


def test_two_process_binning_sync(tmp_path):
    """Reference: per-rank binning of a feature slice + mapper allgather
    (DatasetLoader::ConstructBinMappersFromTextData,
    src/io/dataset_loader.cpp:1079)."""
    script = tmp_path / "binsync_worker.py"
    script.write_text(BINSYNC_TMPL.replace("__REPO__", REPO_ROOT))
    from lightgbm_tpu.parallel.launcher import launch_collect

    rc, outputs = launch_collect(2, [sys.executable, str(script)])
    assert rc == 0, outputs
    digests = []
    for out in outputs:
        for line in out.splitlines():
            if line.startswith("MAPPERHASH"):
                # other libraries' log writes can interleave mid-line;
                # the digest is exactly 64 hex chars
                digests.append(line.split()[1][:64])
    assert len(digests) == 2, f"expected a digest per worker: {outputs}"
    assert len(set(digests)) == 1, f"mappers differ across processes: {digests}"


PRE_PARTITION_TMPL = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import hashlib
    import numpy as np
    from lightgbm_tpu.parallel import init_distributed

    init_distributed()
    rank = jax.process_index()
    rng = np.random.default_rng(99)
    # integer-valued features: quantile binning is partition-invariant, so
    # the mappers match the single-process run exactly and the test isolates
    # the process-local FEEDING + psum path (real-valued distributed binning
    # is rank-local by design, matching dataset_loader.cpp:1079)
    X = rng.integers(0, 63, size=(8000, 6)).astype(np.float64)
    y = X[:, 0] * 0.2 + np.sin(X[:, 1]) + rng.normal(scale=0.3, size=8000)
    lo, hi = rank * 4000, (rank + 1) * 4000
    import lightgbm_tpu as lgb

    params = dict(
        objective="regression", num_leaves=31, min_data_in_leaf=20,
        tree_learner="data", pre_partition=True, verbosity=-1, metric="none",
        max_bin=63,
    )
    d = lgb.Dataset(X[lo:hi], y[lo:hi], params=params)
    b = lgb.train(params, d, 5)
    # the global bin matrix spans both processes but THIS process only
    # holds its own rows
    bins = b._bins
    assert bins.shape[0] == 8000, bins.shape
    local_rows = sum(s.data.shape[0] for s in bins.addressable_shards)
    assert local_rows == 4000, local_rows
    ms = b.model_to_string()
    digest = hashlib.sha256(ms.encode()).hexdigest()
    if rank == 0 and os.environ.get("LGBM_TEST_OUT"):
        open(os.environ["LGBM_TEST_OUT"], "w").write(ms)
    print(f"MODELHASH {digest}")
    """
)


def test_two_process_pre_partition_training(tmp_path):
    """Process-local data feeding (reference: rank-partitioned loading,
    src/io/dataset_loader.cpp:210): two processes train on disjoint halves,
    each holding only its rows on its devices.  The two processes must be
    BIT-IDENTICAL to each other; against a single-process run over the same
    8-shard mesh the tree STRUCTURE must match exactly and leaf values to
    f32 reduction-order tolerance (XLA's cross-process psum reduces in a
    different order than the single-process all-reduce — observed ~1 ulp)."""
    script = tmp_path / "prepart_worker.py"
    script.write_text(PRE_PARTITION_TMPL.replace("__REPO__", REPO_ROOT))
    from lightgbm_tpu.parallel.launcher import launch_collect

    rc, outputs = launch_collect(
        2,
        [sys.executable, str(script)],
        extra_env={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "LGBM_TEST_OUT": str(tmp_path / "worker_model.txt"),
        },
    )
    assert rc == 0, outputs
    digests = []
    for out in outputs:
        for line in out.splitlines():
            if line.startswith("MODELHASH"):
                digests.append(line.split()[1][:64])
    assert len(digests) == 2, f"expected a digest per worker: {outputs}"
    assert len(set(digests)) == 1, f"models differ across processes: {digests}"

    # single-process run over the same global data and mesh width
    import hashlib

    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.default_rng(99)
    X = rng.integers(0, 63, size=(8000, 6)).astype(np.float64)
    y = X[:, 0] * 0.2 + np.sin(X[:, 1]) + rng.normal(scale=0.3, size=8000)
    params = dict(
        objective="regression", num_leaves=31, min_data_in_leaf=20,
        tree_learner="data", verbosity=-1, metric="none", max_bin=63,
    )
    b = lgb.train(params, lgb.Dataset(X, y, params=params), 5)

    def structure_and_values(model_str):
        struct, values = [], []
        for line in model_str.splitlines():
            if line.startswith(
                ("split_feature=", "threshold=", "decision_type=",
                 "left_child=", "right_child=", "num_leaves=")
            ):
                struct.append(line)
            elif line.startswith("leaf_value="):
                values.extend(float(v) for v in line.split("=")[1].split())
        return struct, np.asarray(values)

    # the worker saved its model text next to its hash
    wmodel = (tmp_path / "worker_model.txt").read_text()
    ws, wv = structure_and_values(wmodel)
    ss, sv = structure_and_values(b.model_to_string())
    assert ws == ss, "multi-process split structure != single-process"
    np.testing.assert_allclose(wv, sv, rtol=1e-4, atol=1e-5)


BAGQ_TMPL = textwrap.dedent(
    """
    import os, sys
    sys.path.insert(0, "__REPO__")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import hashlib
    import numpy as np
    from lightgbm_tpu.parallel import init_distributed

    init_distributed()
    rank = jax.process_index()
    rng = np.random.default_rng(44)
    n = 1200
    X = rng.integers(0, 63, size=(n, 4)).astype(np.float64)
    y = rng.integers(0, 4, n).astype(float)
    lo, hi = rank * 600, (rank + 1) * 600
    grp = np.full(30, 20)
    import lightgbm_tpu as lgb

    params = dict(
        objective="lambdarank", tree_learner="data", pre_partition=True,
        bagging_by_query=True, bagging_fraction=0.5, bagging_freq=1,
        verbosity=-1, metric="none", max_bin=63,
    )
    d = lgb.Dataset(X[lo:hi], y[lo:hi], group=grp, params=params)
    b = lgb.train(params, d, 5)
    ms = b.model_to_string()
    print(f"MODELHASH {hashlib.sha256(ms.encode()).hexdigest()}")
    """
)


def test_two_process_bagging_by_query(tmp_path):
    """bagging_by_query under pre_partition: every process builds the same
    global per-query mask (allgathered query sizes with per-block pad
    pseudo-queries), so models must be bit-identical across processes."""
    script = tmp_path / "bagq_worker.py"
    script.write_text(BAGQ_TMPL.replace("__REPO__", REPO_ROOT))
    from lightgbm_tpu.parallel.launcher import launch_collect

    rc, outputs = launch_collect(
        2, [sys.executable, str(script)], coordinator_port=29527
    )
    assert rc == 0, outputs
    digests = []
    for out in outputs:
        for line in out.splitlines():
            if line.startswith("MODELHASH"):
                digests.append(line.split()[1][:64])
    assert len(digests) == 2, f"expected a digest per worker: {outputs}"
    assert len(set(digests)) == 1, f"models differ across processes: {digests}"
