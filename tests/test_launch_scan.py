"""Device-resident boosting (train_steps_per_launch / boosting/launch.py).

The acceptance oracle is BYTE parity: for every eligible config, training
with N>1 iterations fused into one compiled ``lax.scan`` launch must
produce a model dump byte-identical to the N=1 serial loop — across
plain/bagging/GOSS/extra-trees/feature-fraction/multiclass, under
``tree_learner=data`` mesh specs, and composed with ``train_fleet``.  The
second oracle is the compile counter: one train run compiles the scan
executable exactly once (label ``grow/scanN``), proving every launch after
warmup reuses the warm program.  Host-boundary semantics (eval, early
stopping, checkpoints) bucket to launch boundaries; the validator clamps N
to divide every active period.
"""

import os
import re
import tempfile

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.boosting import create_booster
from lightgbm_tpu.boosting.launch import (
    clamp_steps,
    launch_ineligible_reason,
    resolve_launch_steps,
)
from lightgbm_tpu.obs.jit import compile_counts_by_label
from lightgbm_tpu.resilience import NumericsError

RNG = np.random.default_rng(0)
N, F = 400, 12
X = RNG.normal(size=(N, F)).astype(np.float32)
Y = (X[:, 0] * 2 + np.sin(3 * X[:, 1]) + RNG.normal(scale=0.1, size=N)).astype(
    np.float32
)
YBIN = (Y > np.median(Y)).astype(np.float32)
YCLS = RNG.integers(0, 3, size=N).astype(np.float32)

BASE = {
    "objective": "regression",
    "num_leaves": 15,
    "learning_rate": 0.1,
    "min_data_in_leaf": 5,
    "verbosity": -1,
    "seed": 7,
}

# configs whose N=1 vs N>1 dumps must be byte-identical
VARIANTS = {
    "plain": {},
    "bagging": {"bagging_fraction": 0.7, "bagging_freq": 1},
    "bagging_freq2": {
        "bagging_fraction": 0.6, "bagging_freq": 2, "bagging_seed": 11,
    },
    "goss": {
        # learning_rate 0.5 -> GOSS warmup of 2 iterations, so N=4 launches
        # cross the warmup boundary INSIDE the scan
        "boosting": "goss", "learning_rate": 0.5,
        "top_rate": 0.3, "other_rate": 0.3,
    },
    "extra_trees": {"extra_trees": True, "extra_seed": 5},
    "feature_fraction": {"feature_fraction": 0.8},
    "multiclass": {"objective": "multiclass", "num_class": 3},
}


def _strip(dump: str) -> str:
    """Mask the config echoes that legitimately differ between the serial
    reference and the launch run (the requested N itself, and throwaway
    checkpoint paths) — every other byte must match."""
    dump = re.sub(r"\[train_steps_per_launch: [^\]]*\]\n?", "", dump)
    dump = re.sub(r"\[checkpoint_(dir|interval): [^\]]*\]\n?", "", dump)
    return dump


def _label_for(name):
    if name == "multiclass":
        return YCLS
    if name == "binary":
        return YBIN
    return Y


def _fit(extra, label=Y, rounds=8, **train_kw):
    p = dict(BASE)
    p.update(extra)
    ds = lgb.Dataset(X, label=label)
    return lgb.train(p, ds, num_boost_round=rounds, **train_kw)


def _dump(extra, label=Y, rounds=8, **train_kw):
    return _strip(_fit(extra, label, rounds, **train_kw).model_to_string())


_REF_CACHE = {}


def _reference(name):
    if name not in _REF_CACHE:
        extra = dict(VARIANTS[name])
        extra["train_steps_per_launch"] = 1
        _REF_CACHE[name] = _dump(extra, _label_for(name))
    return _REF_CACHE[name]


# ------------------------------------------------------------ byte parity


@pytest.mark.parametrize("name", sorted(VARIANTS))
@pytest.mark.parametrize("n", [2, 4])
def test_launch_parity(name, n):
    extra = dict(VARIANTS[name])
    extra["train_steps_per_launch"] = n
    assert _dump(extra, _label_for(name)) == _reference(name)


def test_launch_parity_n8_full_run_is_one_launch():
    # N == num_boost_round: the whole training run is ONE device dispatch
    extra = {"train_steps_per_launch": 8}
    assert _dump(extra) == _reference("plain")


def test_launch_parity_mesh_data_parallel():
    # conftest forces 8 virtual CPU devices; the psums scan inside shard_map
    extra = {"tree_learner": "data", "num_machines": 8}
    ref = _dump({**extra, "train_steps_per_launch": 1})
    for n in (2, 4):
        assert _dump({**extra, "train_steps_per_launch": n}) == ref


def test_launch_parity_fleet():
    def fleet_dumps(n):
        p = dict(BASE)
        p.update({"num_fleet": 3, "seed": 3, "train_steps_per_launch": n,
                  "bagging_fraction": 0.8, "bagging_freq": 1})
        ds = lgb.Dataset(X, label=Y)
        return [
            _strip(b.model_to_string())
            for b in lgb.train_fleet(p, ds, num_boost_round=8)
        ]

    ref = fleet_dumps(1)
    assert fleet_dumps(2) == ref
    assert fleet_dumps(4) == ref


def test_fleet_mixed_boost_from_average_first_round_fallback():
    # regression: a member with boost_from_average OFF forces the fleet's
    # first-round serial fallback; that fallback must be decided BEFORE
    # any earlier member's boost_from_average score mutation, or the
    # serial round re-applies the boost (models_ still empty) and the
    # earlier member's scores are silently doubled.  Same member count /
    # bagging config as test_launch_parity_fleet so the fleet executables
    # stay warm (boost_from_average is host-side prologue work only).
    def dumps(n):
        members = [
            dict(BASE, seed=3 + i, bagging_fraction=0.8, bagging_freq=1,
                 train_steps_per_launch=n,
                 boost_from_average=(i != 2))
            for i in range(3)
        ]
        ds = lgb.Dataset(X, label=Y)
        return [
            _strip(b.model_to_string())
            for b in lgb.train_fleet(members, ds, num_boost_round=8)
        ]

    ref = dumps(1)
    assert dumps(4) == ref


def test_launch_realigns_after_unaligned_init_model():
    # continue training from an init_model whose iteration count is NOT a
    # multiple of launch_n: the loop must dispatch serially until the
    # window start re-aligns, so periodic host work (eval here) fires on
    # exactly the iterations the serial continuation acts on
    Xv = RNG.normal(size=(100, F)).astype(np.float32)
    Yv = (Xv[:, 0] * 2 + np.sin(3 * Xv[:, 1])
          + RNG.normal(scale=0.1, size=100)).astype(np.float32)
    base = dict(BASE, metric="l2", metric_freq=2)
    ds = lgb.Dataset(X, label=Y)
    init = lgb.train(
        dict(base, train_steps_per_launch=1), ds, num_boost_round=3
    )

    def continue_from_init(n):
        fired = []

        def record(env):
            if env.evaluation_result_list:
                fired.append(env.iteration)

        vs = lgb.Dataset(Xv, label=Yv)
        b = lgb.train(
            dict(base, train_steps_per_launch=n), ds, num_boost_round=5,
            valid_sets=[vs], init_model=init, callbacks=[record],
        )
        return fired, _strip(b.model_to_string())

    ref_fired, ref_dump = continue_from_init(1)
    lau_fired, lau_dump = continue_from_init(2)
    assert lau_fired == ref_fired
    assert lau_dump == ref_dump


def test_launch_parity_early_finish_inside_window():
    # a gain ceiling stops boosting mid-window: the scan's finished latch
    # must reproduce the serial stop point and the rolled-back final round
    extra = {
        "num_leaves": 4, "learning_rate": 0.9, "min_data_in_leaf": 300,
        "min_gain_to_split": 5.0,
    }
    ref_b = _fit({**extra, "train_steps_per_launch": 1}, rounds=12)
    lau_b = _fit({**extra, "train_steps_per_launch": 4}, rounds=12)
    assert lau_b.current_iteration() == ref_b.current_iteration()
    assert _strip(lau_b.model_to_string()) == _strip(ref_b.model_to_string())


# ---------------------------------------------- host-boundary semantics


def test_early_stopping_at_launch_boundary():
    Xv = RNG.normal(size=(100, F)).astype(np.float32)
    Yv = (Xv[:, 0] * 2 + np.sin(3 * Xv[:, 1])
          + RNG.normal(scale=0.1, size=100)).astype(np.float32)

    def fit(n):
        extra = {
            "learning_rate": 0.3, "early_stopping_round": 2,
            "metric": "l2", "metric_freq": 2, "train_steps_per_launch": n,
        }
        p = dict(BASE)
        p.update(extra)
        ds = lgb.Dataset(X, label=Y)
        vs = lgb.Dataset(Xv, label=Yv)
        return lgb.train(p, ds, num_boost_round=40, valid_sets=[vs])

    b1, b2 = fit(1), fit(2)
    # eval fires on the same iterations (metric_freq == N), so early stop
    # lands on the same boundary with the same best model after truncation
    assert b2.best_iteration == b1.best_iteration
    assert _strip(b2.model_to_string(num_iteration=b2.best_iteration)) == \
        _strip(b1.model_to_string(num_iteration=b1.best_iteration))


def test_checkpoint_resume_at_launch_boundary():
    extra = {"bagging_fraction": 0.7, "bagging_freq": 1}
    ref = _dump({**extra, "train_steps_per_launch": 1}, rounds=12)
    with tempfile.TemporaryDirectory() as td:
        ckdir = os.path.join(td, "ck")
        ck = {"checkpoint_dir": ckdir, "checkpoint_interval": 4,
              "train_steps_per_launch": 4}
        assert _dump({**extra, **ck}, rounds=12) == ref
        # kill-and-resume: drop the final checkpoint, resume from iter 8
        for f in os.listdir(ckdir):
            if "12" in f:
                os.remove(os.path.join(ckdir, f))
        resumed = _dump({**extra, **ck}, rounds=12, resume_from=ckdir)
        assert resumed == ref


def test_numerics_error_names_launch_window():
    init = np.zeros(N, np.float64)
    init[0] = np.nan
    p = dict(BASE)
    p.update({"check_numerics": True, "train_steps_per_launch": 4})
    ds = lgb.Dataset(X, label=Y, init_score=init)
    with pytest.raises(NumericsError, match=r"launch window \[0, 4\)"):
        lgb.train(p, ds, num_boost_round=8)


# ------------------------------------------------------- compile counter


def test_one_compile_per_scan_length():
    before = dict(compile_counts_by_label())
    _fit({"train_steps_per_launch": 2}, rounds=8)  # 4 launches
    after = compile_counts_by_label()
    assert after.get("grow/scan2", 0) - before.get("grow/scan2", 0) == 1


def test_host_overhead_gauge_populated():
    b = _fit({"train_steps_per_launch": 2}, rounds=8)
    # wall between device dispatches, one sample per dispatch after the first
    assert len(b._host_overhead_ms) >= 3
    assert all(v >= 0.0 for v in b._host_overhead_ms)
    # the sample window is bounded (long runs must not grow the booster);
    # running totals stay exact for the bench average
    assert b._host_overhead_ms.maxlen == 128
    assert b._host_overhead_n == len(b._host_overhead_ms)
    assert b._host_overhead_total_ms == pytest.approx(
        sum(b._host_overhead_ms)
    )


# ------------------------------------------------------------- validator


def test_clamp_steps_pure():
    assert clamp_steps(8, []) == 8
    assert clamp_steps(8, [4]) == 4
    assert clamp_steps(8, [6]) == 2
    assert clamp_steps(8, [5]) == 1
    assert clamp_steps(8, [4, 6]) == 2
    assert clamp_steps(1, [7]) == 1
    assert clamp_steps(8, [0, -3, 8]) == 8  # inactive periods ignored


def test_config_validation_rejects_bad_values():
    with pytest.raises(ValueError):
        lgb.Config.from_params({"train_steps_per_launch": 0})
    with pytest.raises(ValueError):
        lgb.Config.from_params({"train_steps_per_launch": "sometimes"})


def _booster(extra):
    p = dict(BASE)
    p.update(extra)
    return create_booster(p, lgb.Dataset(X, label=Y))


def test_ineligible_configs_fall_back_to_serial():
    b = _booster({"linear_tree": True, "train_steps_per_launch": 4})
    assert launch_ineligible_reason(b) is not None
    assert resolve_launch_steps(b, has_eval_work=False) == 1
    # and the train entry point still works (serial fallback, same model)
    p = dict(BASE)
    p.update({"linear_tree": True})
    ref = _strip(
        lgb.train({**p, "train_steps_per_launch": 1},
                  lgb.Dataset(X, label=Y), num_boost_round=4
                  ).model_to_string()
    )
    got = _strip(
        lgb.train({**p, "train_steps_per_launch": 4},
                  lgb.Dataset(X, label=Y), num_boost_round=4
                  ).model_to_string()
    )
    assert got == ref


def test_resolve_clamps_to_eval_period():
    b = _booster({"metric_freq": 2, "train_steps_per_launch": 8})
    assert resolve_launch_steps(b, has_eval_work=True) == 2
    # without eval work the period is inactive
    assert resolve_launch_steps(b, has_eval_work=False) == 8


def test_resolve_clamps_to_checkpoint_interval(tmp_path):
    b = _booster({
        "train_steps_per_launch": 8,
        "checkpoint_dir": str(tmp_path), "checkpoint_interval": 6,
    })
    assert resolve_launch_steps(b, has_eval_work=False) == 2


def test_eligible_booster_resolves_requested_n():
    b = _booster({"train_steps_per_launch": 4})
    assert launch_ineligible_reason(b) is None
    assert resolve_launch_steps(b, has_eval_work=False) == 4
