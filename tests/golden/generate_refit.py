"""Generate the refit golden from the reference CLI (task=refit).

    python tests/golden/generate_refit.py /path/to/lightgbm-cli

Trains a model on data A, refits its leaf values on shifted-label data B
(reference GBDT::RefitTree, src/application/application.cpp:229), and
stores both model files + data.  Refit is deterministic given the model
and data, so the parity test compares our Booster.refit leaf values
directly against the reference's refit output."""

import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

OUT = Path(__file__).parent

TRAIN = """task = train
objective = regression
data = train.csv
label_column = 0
num_trees = 6
learning_rate = 0.2
num_leaves = 15
min_data_in_leaf = 20
verbosity = -1
output_model = model.txt
"""

REFIT = """task = refit
data = refit.csv
label_column = 0
input_model = model.txt
output_model = refit_model.txt
refit_decay_rate = 0.9
verbosity = -1
"""


def main(cli: str) -> None:
    cli = str(Path(cli).resolve())
    rng = np.random.default_rng(17)
    n = 3000
    X = rng.normal(size=(n, 4))
    y = 1.5 * X[:, 0] - X[:, 1] + rng.normal(scale=0.2, size=n)
    y2 = y + 0.8 * np.sin(X[:, 2])  # shifted labels for the refit
    with tempfile.TemporaryDirectory() as td:
        work = Path(td)
        np.savetxt(work / "train.csv", np.column_stack([y, X]),
                   delimiter=",", fmt="%.8f")
        np.savetxt(work / "refit.csv", np.column_stack([y2, X]),
                   delimiter=",", fmt="%.8f")
        (work / "train.conf").write_text(TRAIN)
        p = subprocess.run([cli, "config=train.conf"], cwd=work,
                           capture_output=True, text=True)
        if p.returncode != 0:
            raise RuntimeError(p.stdout + p.stderr)
        (work / "refit.conf").write_text(REFIT)
        p2 = subprocess.run([cli, "config=refit.conf"], cwd=work,
                            capture_output=True, text=True)
        if p2.returncode != 0:
            raise RuntimeError(p2.stdout + p2.stderr)
        OUT.joinpath("refit.train.csv").write_text(
            (work / "train.csv").read_text())
        OUT.joinpath("refit.refit.csv").write_text(
            (work / "refit.csv").read_text())
        OUT.joinpath("refit.model.txt").write_text(
            (work / "model.txt").read_text())
        OUT.joinpath("refit.refit_model.txt").write_text(
            (work / "refit_model.txt").read_text())
    print("refit goldens written")


if __name__ == "__main__":
    main(sys.argv[1])
