"""Generate the forced-bins golden fixture from the reference CLI.

Run ONCE with the reference built (cmake out-of-tree works — copy the
source somewhere writable and lower cmake_minimum_required if the local
cmake is older):

    python tests/golden/generate_forcedbins.py /path/to/lightgbm-cli

Writes: forcedbins.train.csv (label first), forcedbins.bounds.json (the
forced bounds file), forcedbins.model.txt, forcedbins.preds.txt.
tests/test_consistency.py's forced-bins golden test then compares our
forced-bins training against these without needing the binary.
"""

import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

OUT = Path(__file__).parent

FORCED = '[{"feature": 0, "bin_upper_bound": [-3.0, 1.25, 2.5]}]'


def make_data():
    rng = np.random.default_rng(42)
    n = 2000
    f0 = rng.uniform(-10, 10, size=n)
    f1 = rng.normal(size=n)
    f2 = rng.uniform(0, 1, size=n)
    # the informative step sits at a forced boundary (1.25): both engines
    # must be able to split exactly there
    y = 2.0 * (f0 > 1.25) + 0.5 * f1 + rng.normal(scale=0.1, size=n)
    return np.column_stack([y, f0, f1, f2])


PARAMS = """task = train
objective = regression
data = train.csv
num_trees = 8
learning_rate = 0.2
num_leaves = 8
max_bin = 16
min_data_in_leaf = 20
forcedbins_filename = forced.json
is_training_metric = true
metric = l2
verbosity = 2
output_model = model.txt
"""


def main(cli: str) -> None:
    cli = str(Path(cli).resolve())  # subprocess cwd changes; pin the binary
    arr = make_data()
    with tempfile.TemporaryDirectory() as td:
        work = Path(td)
        np.savetxt(work / "train.csv", arr, delimiter=",", fmt="%.8f")
        (work / "forced.json").write_text(FORCED)
        (work / "train.conf").write_text(PARAMS)
        p = subprocess.run(
            [cli, "config=train.conf"], cwd=work, capture_output=True,
            text=True,
        )
        if p.returncode != 0:
            raise RuntimeError(p.stdout + p.stderr)
        (work / "pred.conf").write_text(
            "task = predict\ndata = train.csv\ninput_model = model.txt\n"
            "output_result = preds.txt\n"
        )
        p2 = subprocess.run(
            [cli, "config=pred.conf"], cwd=work, capture_output=True,
            text=True,
        )
        if p2.returncode != 0:
            raise RuntimeError(p2.stdout + p2.stderr)
        OUT.joinpath("forcedbins.train.csv").write_text(
            (work / "train.csv").read_text()
        )
        OUT.joinpath("forcedbins.bounds.json").write_text(FORCED)
        OUT.joinpath("forcedbins.model.txt").write_text(
            (work / "model.txt").read_text()
        )
        OUT.joinpath("forcedbins.preds.txt").write_text(
            (work / "preds.txt").read_text()
        )
    print("forced-bins goldens written")


if __name__ == "__main__":
    main(sys.argv[1])
