"""Generate golden parity data from the reference LightGBM CLI.

Run ONCE in an environment with the reference built (see
tests/test_consistency.py docstring):

    python tests/golden/generate.py /path/to/lightgbm-cli

For each of the four reference examples this trains with the example's
train.conf, records the eval trajectory, the trained model file, and the
model's predictions on the example's test set. Tests then compare our
training/eval/prediction against these WITHOUT needing the reference binary.
"""

import json
import re
import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

EXAMPLES = {
    "regression": "regression",
    "binary_classification": "binary",
    "lambdarank": "rank",
    "multiclass_classification": "multiclass",
}
REF_EXAMPLES = Path("/root/reference/examples")
OUT = Path(__file__).parent


def run_example(cli: str, name: str, stem: str) -> None:
    src = REF_EXAMPLES / name
    with tempfile.TemporaryDirectory() as td:
        work = Path(td)
        for f in src.iterdir():
            if f.is_file():
                shutil.copy(f, work / f.name)
        # train
        p = subprocess.run(
            [cli, "config=train.conf"], cwd=work, capture_output=True, text=True
        )
        log = p.stdout + p.stderr
        if p.returncode != 0:
            raise RuntimeError(f"{name}: train failed\n{log}")
        # eval trajectory lines look like:
        # [LightGBM] [Info] Iteration:N, training <metric> : <value>
        evals = {}
        for m in re.finditer(
            r"Iteration:(\d+), (\S+) (\S+) : ([-\d.eE]+)", log
        ):
            it, dsname, metric, val = m.groups()
            evals.setdefault(f"{dsname}:{metric}", []).append(
                [int(it), float(val)]
            )
        model_file = work / "LightGBM_model.txt"
        model_text = model_file.read_text()
        # predict on the example's test file
        pred_conf = work / "golden_predict.conf"
        pred_conf.write_text(
            f"task = predict\ndata = {stem}.test\n"
            "input_model = LightGBM_model.txt\n"
            "output_result = golden_preds.txt\n"
        )
        p2 = subprocess.run(
            [cli, "config=golden_predict.conf"],
            cwd=work,
            capture_output=True,
            text=True,
        )
        if p2.returncode != 0:
            raise RuntimeError(f"{name}: predict failed\n{p2.stdout}{p2.stderr}")
        preds = (work / "golden_preds.txt").read_text()
    (OUT / f"{name}.model.txt").write_text(model_text)
    (OUT / f"{name}.preds.txt").write_text(preds)
    (OUT / f"{name}.evals.json").write_text(json.dumps(evals, indent=1))
    final = {k: v[-1] for k, v in evals.items()}
    print(f"{name}: {final}")


def main() -> None:
    cli = sys.argv[1] if len(sys.argv) > 1 else "/tmp/ref_build/lightgbm"
    for name, stem in EXAMPLES.items():
        run_example(cli, name, stem)


if __name__ == "__main__":
    main()
