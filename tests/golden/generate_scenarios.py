"""Generate golden fixtures from the reference CLI for feature scenarios
beyond the four stock examples: monotone constraints, CEGB, quantized
gradients, wide bins (max_bin 1024), and GOSS.

    python tests/golden/generate_scenarios.py /path/to/lightgbm-cli

Per scenario writes: scen_<name>.train.csv, scen_<name>.model.txt,
scen_<name>.preds.txt, scen_<name>.evals.json.
tests/test_consistency.py::test_scenario_golden_parity consumes them
(cross-load + quality parity) without needing the binary.
"""

import json
import re
import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

OUT = Path(__file__).parent

IO_CONF = """task = train
data = train.csv
label_column = 0
is_training_metric = true
verbosity = 2
output_model = model.txt
"""

# training params shared by every scenario; the per-scenario extras merge
# OVER these (single dict — the reference CLI warns on duplicate keys).
# num_trees rides along so the parity test trains the same round count.
BASE_PARAMS = {
    "objective": "regression",
    "num_trees": 10,
    "learning_rate": 0.15,
    "num_leaves": 31,
    "min_data_in_leaf": 20,
    "metric": "l2",
}


def _data(seed=7, n=4000, f=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (
        1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * np.sin(2 * X[:, 2])
        + rng.normal(scale=0.2, size=n)
    )
    return np.column_stack([y, X])


# per-scenario EXTRA params, single source of truth: the CLI conf is
# rendered from these AND they are emitted as scen_<name>.params.json for
# the parity test to rebuild its param dict from — nothing to keep in sync
# by hand
SCENARIOS = {
    # advanced monotone ladder evidence against the reference's own result
    "monotone_basic": ({"monotone_constraints": [1, -1, 0, 0],
                        "monotone_constraints_method": "basic"}, _data),
    "monotone_advanced": ({"monotone_constraints": [1, -1, 0, 0],
                           "monotone_constraints_method": "advanced"},
                          _data),
    "cegb": ({"cegb_tradeoff": 1.0,
              "cegb_penalty_feature_coupled": [0.5, 0.5, 0.5, 0.5],
              "cegb_penalty_split": 1e-5}, _data),
    "quantized": ({"use_quantized_grad": True, "num_grad_quant_bins": 4},
                  _data),
    "widebin": ({"max_bin": 1024}, lambda: _data(seed=9, n=20000, f=4)),
    "goss": ({"boosting": "goss", "top_rate": 0.2, "other_rate": 0.1},
             lambda: _data(seed=11, n=8000, f=4)),
}


def _pos_data(seed=13, n=4000, f=4):
    """Positive labels for the count/positive-continuous objectives."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    mu = np.exp(0.6 * X[:, 0] - 0.4 * X[:, 1])
    y = rng.poisson(mu).astype(np.float64) + rng.uniform(0, 0.2, size=n)
    return np.column_stack([y, X])


# objective-family trajectories: metric name must match the objective's
# default so the eval key in the fixture is predictable
SCENARIOS.update({
    "obj_tweedie": ({"objective": "tweedie", "tweedie_variance_power": 1.3,
                     "metric": "tweedie"}, _pos_data),
    "obj_poisson": ({"objective": "poisson", "metric": "poisson"},
                    _pos_data),
    "obj_quantile": ({"objective": "quantile", "alpha": 0.7,
                      "metric": "quantile"}, _data),
    "obj_huber": ({"objective": "huber", "alpha": 0.9, "metric": "huber"},
                  _data),
    "obj_gamma": ({"objective": "gamma", "metric": "gamma"}, _pos_data),
    "obj_fair": ({"objective": "fair", "fair_c": 1.5, "metric": "fair"},
                 _data),
    "obj_mape": ({"objective": "mape", "metric": "mape"}, _pos_data),
    "obj_l1": ({"objective": "regression_l1", "metric": "l1"}, _data),
    # stochastic modes: cross-engine RNG streams differ by design, the
    # parity test's band absorbs it
    "dart": ({"boosting": "dart", "drop_rate": 0.15, "metric": "l2"},
             _data),
    "bagging": ({"bagging_fraction": 0.7, "bagging_freq": 1,
                 "feature_fraction": 0.8, "metric": "l2"},
                lambda: _data(seed=21, n=6000, f=4)),
})


def _prob_data(seed=31, n=4000, f=4):
    """Labels in [0, 1] for the cross-entropy family."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    p = 1.0 / (1.0 + np.exp(-(1.1 * X[:, 0] - 0.7 * X[:, 1])))
    y = np.clip(p + rng.normal(scale=0.08, size=n), 0.0, 1.0)
    return np.column_stack([y, X])


def _cat_data(seed=41, n=4000):
    """Feature 3 is an integer category whose subset {2, 5, 9} drives y."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    X[:, 3] = rng.integers(0, 12, size=n)
    y = (
        0.8 * X[:, 0] + np.where(np.isin(X[:, 3], [2, 5, 9]), 1.5, -0.5)
        + rng.normal(scale=0.3, size=n)
    )
    return np.column_stack([y, X])


def _weighted_data(seed=37, n=4000, f=4):
    """(arr, sidecars): per-row weights emphasizing half the rows."""
    arr = _data(seed=seed, n=n, f=f)
    rng = np.random.default_rng(seed + 1)
    w = np.where(rng.random(n) < 0.5, 3.0, 0.5)
    return arr, {"weight": w}


SCENARIOS.update({
    "obj_xentropy": ({"objective": "cross_entropy",
                      "metric": "cross_entropy"}, _prob_data),
    "obj_xentlambda": ({"objective": "cross_entropy_lambda",
                        "metric": "cross_entropy_lambda"}, _prob_data),
    "weighted": ({"metric": "l2"}, _weighted_data),
    # 3-tuples carry AUX FILES the conf references by bare filename; the
    # parity test rewrites *_filename params to the fixture copies
    "interaction": ({"interaction_constraints": "[0,1],[2,3]"}, _data),
    "categorical": (
        {"categorical_feature": "3", "min_data_per_group": 5,
         "cat_smooth": 2.0}, lambda: _cat_data(),
    ),
    # the reference build links Eigen (tensorflow wheel headers), so
    # linear trees golden-compare too
    "linear": ({"linear_tree": True, "linear_lambda": 0.1}, _data),
    "forcedsplits": (
        {"forcedsplits_filename": "forced_splits.json"}, _data,
        {"forced_splits.json":
         '{"feature": 2, "threshold": 0.5, '
         '"left": {"feature": 3, "threshold": -0.25}}'},
    ),
})


def _onehot_data(seed=51, n=4000, nvar=6, ncat=12):
    """Block one-hot design for the EFB bundling scenario: nvar categorical
    variables one-hot encoded into nvar*ncat mutually-exclusive-within-block
    columns.  Both engines bundle it (reference FindGroups, our
    bundling.py) and must land on the same trees in original-feature
    space."""
    rng = np.random.default_rng(seed)
    codes = rng.integers(0, ncat, size=(n, nvar))
    X = np.zeros((n, nvar * ncat))
    X[np.arange(n)[:, None], np.arange(nvar) * ncat + codes] = 1.0
    w = rng.normal(size=nvar * ncat)
    y = X @ w + 0.2 * rng.normal(size=n)
    return np.column_stack([y, X])


SCENARIOS.update({
    # EFB: explicit enable_bundle so the params.json documents the feature
    # under test (it is the default in both engines)
    "bundle": ({"enable_bundle": True, "min_data_in_leaf": 5,
                "metric": "l2"}, _onehot_data),
})


def _conf_value(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, list):
        return ",".join(str(x) for x in v)
    return str(v)


def main(cli: str) -> None:
    cli = str(Path(cli).resolve())
    for name, scen in SCENARIOS.items():
        extra, mk = scen[0], scen[1]
        aux_files = scen[2] if len(scen) > 2 else {}
        merged = {**BASE_PARAMS, **extra}
        conf = IO_CONF + "".join(
            f"{k} = {_conf_value(v)}\n" for k, v in merged.items()
        )
        made = mk()
        arr, sidecars = made if isinstance(made, tuple) else (made, {})
        with tempfile.TemporaryDirectory() as td:
            work = Path(td)
            np.savetxt(work / "train.csv", arr, delimiter=",", fmt="%.8f")
            for side, vals in sidecars.items():
                np.savetxt(work / f"train.csv.{side}", vals, fmt="%.8f")
            for fname, content in aux_files.items():
                (work / fname).write_text(content)
                OUT.joinpath(f"scen_{name}.{fname}").write_text(content)
            (work / "train.conf").write_text(conf)
            p = subprocess.run(
                [cli, "config=train.conf"], cwd=work, capture_output=True,
                text=True,
            )
            if p.returncode != 0:
                raise RuntimeError(f"{name}:\n{p.stdout}{p.stderr}")
            log = p.stdout + p.stderr
            evals = {}
            for m in re.finditer(
                r"Iteration:(\d+), (\S+) (\S+) : ([-\d.eE]+)", log
            ):
                it, dsname, metric, val = m.groups()
                evals.setdefault(f"{dsname}:{metric}", []).append(
                    [int(it), float(val)]
                )
            (work / "pred.conf").write_text(
                "task = predict\ndata = train.csv\n"
                "input_model = model.txt\noutput_result = preds.txt\n"
            )
            p2 = subprocess.run(
                [cli, "config=pred.conf"], cwd=work, capture_output=True,
                text=True,
            )
            if p2.returncode != 0:
                raise RuntimeError(f"{name} predict:\n{p2.stdout}{p2.stderr}")
            OUT.joinpath(f"scen_{name}.train.csv").write_text(
                (work / "train.csv").read_text()
            )
            for side in sidecars:
                OUT.joinpath(f"scen_{name}.train.csv.{side}").write_text(
                    (work / f"train.csv.{side}").read_text()
                )
            OUT.joinpath(f"scen_{name}.model.txt").write_text(
                (work / "model.txt").read_text()
            )
            OUT.joinpath(f"scen_{name}.preds.txt").write_text(
                (work / "preds.txt").read_text()
            )
            OUT.joinpath(f"scen_{name}.evals.json").write_text(
                json.dumps(evals, indent=1)
            )
            OUT.joinpath(f"scen_{name}.params.json").write_text(
                json.dumps(merged, indent=1)
            )
            final = {k: v[-1][1] for k, v in evals.items()}
            print(f"{name}: {final}")


if __name__ == "__main__":
    main(sys.argv[1])
