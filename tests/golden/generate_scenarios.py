"""Generate golden fixtures from the reference CLI for feature scenarios
beyond the four stock examples: monotone constraints, CEGB, quantized
gradients, wide bins (max_bin 1024), and GOSS.

    python tests/golden/generate_scenarios.py /path/to/lightgbm-cli

Per scenario writes: scen_<name>.train.csv, scen_<name>.model.txt,
scen_<name>.preds.txt, scen_<name>.evals.json.
tests/test_consistency.py::test_scenario_golden_parity consumes them
(cross-load + quality parity) without needing the binary.
"""

import json
import re
import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

OUT = Path(__file__).parent

BASE = """task = train
objective = regression
data = train.csv
label_column = 0
num_trees = 10
learning_rate = 0.15
num_leaves = 31
min_data_in_leaf = 20
is_training_metric = true
metric = l2
verbosity = 2
output_model = model.txt
"""


def _data(seed=7, n=4000, f=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (
        1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * np.sin(2 * X[:, 2])
        + rng.normal(scale=0.2, size=n)
    )
    return np.column_stack([y, X])


# per-scenario EXTRA params, single source of truth: the CLI conf is
# rendered from these AND they are emitted as scen_<name>.params.json for
# the parity test to rebuild its param dict from — nothing to keep in sync
# by hand
SCENARIOS = {
    # advanced monotone ladder evidence against the reference's own result
    "monotone_basic": ({"monotone_constraints": [1, -1, 0, 0],
                        "monotone_constraints_method": "basic"}, _data),
    "monotone_advanced": ({"monotone_constraints": [1, -1, 0, 0],
                           "monotone_constraints_method": "advanced"},
                          _data),
    "cegb": ({"cegb_tradeoff": 1.0,
              "cegb_penalty_feature_coupled": [0.5, 0.5, 0.5, 0.5],
              "cegb_penalty_split": 1e-5}, _data),
    "quantized": ({"use_quantized_grad": True, "num_grad_quant_bins": 4},
                  _data),
    "widebin": ({"max_bin": 1024}, lambda: _data(seed=9, n=20000, f=4)),
    "goss": ({"boosting": "goss", "top_rate": 0.2, "other_rate": 0.1},
             lambda: _data(seed=11, n=8000, f=4)),
}


def _conf_value(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, list):
        return ",".join(str(x) for x in v)
    return str(v)


def main(cli: str) -> None:
    cli = str(Path(cli).resolve())
    for name, (extra, mk) in SCENARIOS.items():
        conf = BASE + "".join(
            f"{k} = {_conf_value(v)}\n" for k, v in extra.items()
        )
        arr = mk()
        with tempfile.TemporaryDirectory() as td:
            work = Path(td)
            np.savetxt(work / "train.csv", arr, delimiter=",", fmt="%.8f")
            (work / "train.conf").write_text(conf)
            p = subprocess.run(
                [cli, "config=train.conf"], cwd=work, capture_output=True,
                text=True,
            )
            if p.returncode != 0:
                raise RuntimeError(f"{name}:\n{p.stdout}{p.stderr}")
            log = p.stdout + p.stderr
            evals = {}
            for m in re.finditer(
                r"Iteration:(\d+), (\S+) (\S+) : ([-\d.eE]+)", log
            ):
                it, dsname, metric, val = m.groups()
                evals.setdefault(f"{dsname}:{metric}", []).append(
                    [int(it), float(val)]
                )
            (work / "pred.conf").write_text(
                "task = predict\ndata = train.csv\n"
                "input_model = model.txt\noutput_result = preds.txt\n"
            )
            p2 = subprocess.run(
                [cli, "config=pred.conf"], cwd=work, capture_output=True,
                text=True,
            )
            if p2.returncode != 0:
                raise RuntimeError(f"{name} predict:\n{p2.stdout}{p2.stderr}")
            OUT.joinpath(f"scen_{name}.train.csv").write_text(
                (work / "train.csv").read_text()
            )
            OUT.joinpath(f"scen_{name}.model.txt").write_text(
                (work / "model.txt").read_text()
            )
            OUT.joinpath(f"scen_{name}.preds.txt").write_text(
                (work / "preds.txt").read_text()
            )
            OUT.joinpath(f"scen_{name}.evals.json").write_text(
                json.dumps(evals, indent=1)
            )
            OUT.joinpath(f"scen_{name}.params.json").write_text(
                json.dumps(extra, indent=1)
            )
            final = {k: v[-1][1] for k, v in evals.items()}
            print(f"{name}: {final}")


if __name__ == "__main__":
    main(sys.argv[1])
