"""Generate the position-debias golden from the reference CLI.

    python tests/golden/generate_position.py /path/to/lightgbm-cli

Unbiased lambdarank activates in the reference when a ``<data>.position``
sidecar is present (Metadata::LoadPositions, src/io/metadata.cpp:663).
Writes position.train.csv + .query + .position sidecars, the reference's
model, and its eval trajectory (ndcg@3)."""

import json
import re
import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

OUT = Path(__file__).parent

CONF = """task = train
objective = lambdarank
data = train.csv
label_column = 0
num_trees = 10
learning_rate = 0.15
num_leaves = 31
min_data_in_leaf = 10
is_training_metric = true
metric = ndcg
eval_at = 3
verbosity = 2
output_model = model.txt
lambdarank_position_bias_regularization = 0.5
"""


def make_data():
    rng = np.random.default_rng(29)
    groups, per = 100, 30
    n = groups * per
    X = rng.normal(size=(n, 4))
    rel = 1.2 * X[:, 0] + 0.6 * X[:, 1] + rng.normal(scale=0.5, size=n)
    y = np.digitize(rel, np.quantile(rel, [0.5, 0.8, 0.95])).astype(float)
    # synthetic presentation positions: mostly relevance-ordered with noise,
    # so the position signal is informative but not degenerate
    pos = np.zeros(n, np.int32)
    for g in range(groups):
        sl = slice(g * per, (g + 1) * per)
        order = np.argsort(-(rel[sl] + rng.normal(scale=1.0, size=per)))
        pos[sl][order] = np.arange(per)
    return X, y, np.full(groups, per), pos


def main(cli: str) -> None:
    cli = str(Path(cli).resolve())
    X, y, group, pos = make_data()
    with tempfile.TemporaryDirectory() as td:
        work = Path(td)
        np.savetxt(work / "train.csv", np.column_stack([y, X]),
                   delimiter=",", fmt="%.8f")
        np.savetxt(work / "train.csv.query", group, fmt="%d")
        np.savetxt(work / "train.csv.position", pos, fmt="%d")
        (work / "train.conf").write_text(CONF)
        p = subprocess.run([cli, "config=train.conf"], cwd=work,
                           capture_output=True, text=True)
        if p.returncode != 0:
            raise RuntimeError(p.stdout + p.stderr)
        log = p.stdout + p.stderr
        evals = {}
        for m in re.finditer(
            r"Iteration:(\d+), (\S+) (\S+) : ([-\d.eE]+)", log
        ):
            it, dsname, metric, val = m.groups()
            evals.setdefault(f"{dsname}:{metric}", []).append(
                [int(it), float(val)]
            )
        for src, dst in (
            ("train.csv", "position.train.csv"),
            ("train.csv.query", "position.train.csv.query"),
            ("train.csv.position", "position.train.csv.position"),
            ("model.txt", "position.model.txt"),
        ):
            OUT.joinpath(dst).write_text((work / src).read_text())
        OUT.joinpath("position.evals.json").write_text(
            json.dumps(evals, indent=1)
        )
        print("position goldens:", {k: v[-1] for k, v in evals.items()})


if __name__ == "__main__":
    main(sys.argv[1])
