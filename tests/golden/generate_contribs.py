"""Generate SHAP-contribution goldens from the reference CLI.

    python tests/golden/generate_contribs.py /path/to/lightgbm-cli

For existing golden models (forcedbins + monotone_basic scenario), runs
``task=predict predict_contrib=true`` over the model's own train.csv and
stores the per-feature contribution matrix.  Contributions are
DETERMINISTIC given the model file, so the parity test compares our
TreeSHAP (shap.py pred_contrib) tightly against the reference's — much
stronger than quality-band checks."""

import subprocess
import sys
import tempfile
from pathlib import Path

OUT = Path(__file__).parent

MODELS = ["forcedbins", "scen_monotone_basic"]


def main(cli: str) -> None:
    cli = str(Path(cli).resolve())
    for stem in MODELS:
        model = OUT / f"{stem}.model.txt"
        data = OUT / f"{stem}.train.csv"
        with tempfile.TemporaryDirectory() as td:
            work = Path(td)
            # contributions are computed on the FEATURE columns; the train
            # csv has the label first, which predict would treat as a
            # feature — strip it
            import numpy as np

            arr = np.loadtxt(data, delimiter=",")
            np.savetxt(work / "pred.csv", arr[:500, 1:], delimiter=",",
                       fmt="%.8f")
            (work / "model.txt").write_text(model.read_text())
            (work / "pred.conf").write_text(
                "task = predict\ndata = pred.csv\ninput_model = model.txt\n"
                "output_result = contribs.txt\npredict_contrib = true\n"
                "header = false\n"
            )
            p = subprocess.run([cli, "config=pred.conf"], cwd=work,
                               capture_output=True, text=True)
            if p.returncode != 0:
                raise RuntimeError(f"{stem}:\n{p.stdout}{p.stderr}")
            OUT.joinpath(f"{stem}.contribs.txt").write_text(
                (work / "contribs.txt").read_text()
            )
        print(f"{stem}: contribs written")


if __name__ == "__main__":
    main(sys.argv[1])
