"""Data-parallel semantics on a virtual 8-device CPU mesh.

Mirrors the reference's distributed tests (tests/distributed/
_test_distributed.py: N localhost processes, assert per-worker model
equality): here the assertion is that the mesh-sharded grower produces the
IDENTICAL tree to the single-device grower — the psum reproduces the
histogram ReduceScatter + split Allreduce semantics exactly.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from lightgbm_tpu.ops.grower import GrowerParams, grow_tree  # noqa: E402
from lightgbm_tpu.parallel import (  # noqa: E402
    DATA_AXIS,
    l2_gradients,
    make_data_parallel_train_step,
    replicate,
    shard_rows,
)

N, F, MAX_BIN = 512, 6, 16


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(21)
    bins = rng.integers(0, MAX_BIN - 1, size=(N, F), dtype=np.int32)
    label = (bins[:, 0] * 0.3 - bins[:, 1] * 0.1 + rng.normal(size=N)).astype(
        np.float32
    )
    return bins, label


def _single_device_tree(bins, label, params, device):
    # Pin the reference run to the SAME platform as the mesh (CPU): the tree
    # must be identical to the sharded run, and cross-backend f32 reduction
    # order differences can legitimately flip near-tied splits.
    put = lambda x: jax.device_put(x, device)  # noqa: E731
    grad = put(0.0 - np.asarray(label))
    hess = put(np.ones(N, np.float32))
    tree, leaf_id = grow_tree(
        put(np.asarray(bins)),
        grad,
        hess,
        put(np.ones(N, np.float32)),
        put(np.full((F,), MAX_BIN, np.int32)),
        put(np.full((F,), -1, np.int32)),
        put(np.ones((F,), bool)),
        params,
    )
    return tree, leaf_id


def test_sharded_tree_equals_single_device(problem, cpu_mesh_devices):
    bins, label = problem
    params_local = GrowerParams(num_leaves=15, max_bin=MAX_BIN, min_data_in_leaf=5)
    tree_ref, _ = _single_device_tree(bins, label, params_local, cpu_mesh_devices[0])

    mesh = Mesh(np.array(cpu_mesh_devices[:8]), (DATA_AXIS,))
    params_mesh = GrowerParams(
        num_leaves=15, max_bin=MAX_BIN, min_data_in_leaf=5, axis_name=DATA_AXIS
    )
    step = make_data_parallel_train_step(mesh, params_mesh, 0.1, l2_gradients)
    score = shard_rows(np.zeros(N, np.float32), mesh)
    new_score, tree = step(
        shard_rows(bins, mesh),
        shard_rows(label, mesh),
        score,
        replicate(np.full(F, MAX_BIN, np.int32), mesh),
        replicate(np.full(F, -1, np.int32), mesh),
        replicate(np.ones(F, bool), mesh),
    )

    assert int(tree.num_leaves) == int(tree_ref.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(tree.split_feature), np.asarray(tree_ref.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(tree.split_bin), np.asarray(tree_ref.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(tree.leaf_value), np.asarray(tree_ref.leaf_value), rtol=1e-4, atol=1e-5
    )


def test_booster_data_parallel_matches_serial(cpu_mesh_devices):
    """e2e: lgb.train(tree_learner='data') over the 8-CPU mesh reproduces
    serial training (reference: tests/distributed/_test_distributed.py
    asserts the same for N localhost worker processes)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    n, f = 1000, 8
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 1.5 - X[:, 1] + 0.5 * X[:, 2] + rng.normal(scale=0.1, size=n)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "min_data_in_leaf": 5,
        "learning_rate": 0.2,
        "verbosity": -1,
        "metric": "l2",
        "seed": 3,
    }
    serial = lgb.train(params, lgb.Dataset(X, y), num_boost_round=10)
    dist = lgb.train(
        {**params, "tree_learner": "data"}, lgb.Dataset(X, y), num_boost_round=10
    )
    np.testing.assert_allclose(
        dist.predict(X), serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_booster_data_parallel_padded_rows(cpu_mesh_devices):
    """n not divisible by the mesh: weight-0 padded rows must not change the
    model."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(11)
    n, f = 997, 5  # 997 % 8 = 5 -> 3 padding rows
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    params = {
        "objective": "binary",
        "num_leaves": 7,
        "min_data_in_leaf": 5,
        "learning_rate": 0.1,
        "verbosity": -1,
        "metric": "binary_logloss",
        "seed": 3,
    }
    serial = lgb.train(params, lgb.Dataset(X, y), num_boost_round=8)
    dist = lgb.train(
        {**params, "tree_learner": "data"}, lgb.Dataset(X, y), num_boost_round=8
    )
    np.testing.assert_allclose(
        dist.predict(X), serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_booster_data_parallel_multiclass_valid(cpu_mesh_devices):
    """Multi-class + valid-set eval under the mesh: per-class trees and the
    sharded valid score walk must match serial."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(5)
    n, f = 600, 6
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * rng.normal(size=n) > 0).astype(int) + (
        X[:, 1] > 0.5
    ).astype(int)
    Xv = rng.normal(size=(200, f))
    yv = (Xv[:, 0] > 0).astype(int) + (Xv[:, 1] > 0.5).astype(int)
    params = {
        "objective": "multiclass",
        "num_class": 3,
        "num_leaves": 7,
        "min_data_in_leaf": 5,
        "verbosity": -1,
        "metric": "multi_logloss",
        "seed": 1,
    }
    evals_s, evals_d = {}, {}
    dtrain = lgb.Dataset(X, y)
    serial = lgb.train(
        params,
        dtrain,
        num_boost_round=5,
        valid_sets=[lgb.Dataset(Xv, yv, reference=dtrain)],
        valid_names=["v"],
        callbacks=[lgb.record_evaluation(evals_s)],
    )
    dtrain2 = lgb.Dataset(X, y)
    dist = lgb.train(
        {**params, "tree_learner": "data"},
        dtrain2,
        num_boost_round=5,
        valid_sets=[lgb.Dataset(Xv, yv, reference=dtrain2)],
        valid_names=["v"],
        callbacks=[lgb.record_evaluation(evals_d)],
    )
    np.testing.assert_allclose(
        dist.predict(X), serial.predict(X), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        evals_d["v"]["multi_logloss"],
        evals_s["v"]["multi_logloss"],
        rtol=1e-5,
    )


def test_booster_data_parallel_xentlambda_padded(cpu_mesh_devices):
    """cross_entropy_lambda has NON-multiplicative weights (z-transform,
    xentropy_objective.hpp:184): padded rows must be zeroed via explicit
    gradient masking, not synthetic weights (which would change its math)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    n, f = 997, 6  # 3 padding rows on the 8-device mesh
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {
        "objective": "cross_entropy_lambda",
        "num_leaves": 7,
        "verbosity": -1,
        "seed": 3,
    }
    serial = lgb.train(params, lgb.Dataset(X, y), num_boost_round=5)
    dist = lgb.train(
        {**params, "tree_learner": "data"}, lgb.Dataset(X, y), num_boost_round=5
    )
    assert np.isfinite(dist.predict(X)).all()
    np.testing.assert_allclose(
        dist.predict(X), serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_booster_data_parallel_bagging_runs(cpu_mesh_devices):
    """Bagging + GOSS masks under the mesh: loss must decrease (masks differ
    from serial because the padded draw shape differs, so no exact match)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(9)
    n, f = 800, 6
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2.0 - X[:, 1] + rng.normal(scale=0.1, size=n)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "bagging_fraction": 0.7,
        "bagging_freq": 1,
        "learning_rate": 0.2,
        "verbosity": -1,
        "tree_learner": "data",
        "seed": 3,
    }
    bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=15)
    pred = bst.predict(X)
    assert np.mean((pred - y) ** 2) < 0.5 * np.var(y)


def test_sharded_score_update_correct(problem, cpu_mesh_devices):
    bins, label = problem
    mesh = Mesh(np.array(cpu_mesh_devices[:8]), (DATA_AXIS,))
    params_mesh = GrowerParams(
        num_leaves=15, max_bin=MAX_BIN, min_data_in_leaf=5, axis_name=DATA_AXIS
    )
    step = make_data_parallel_train_step(mesh, params_mesh, 0.1, l2_gradients)
    score0 = shard_rows(np.zeros(N, np.float32), mesh)
    new_score, tree = step(
        shard_rows(bins, mesh),
        shard_rows(label, mesh),
        score0,
        replicate(np.full(F, MAX_BIN, np.int32), mesh),
        replicate(np.full(F, -1, np.int32), mesh),
        replicate(np.ones(F, bool), mesh),
    )
    # one boosting step on L2 must reduce the loss
    s = np.asarray(new_score)
    assert np.mean((s - label) ** 2) < np.mean(label**2)
    # sharding preserved
    assert "data" in str(new_score.sharding.spec)
