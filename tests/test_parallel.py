"""Data-parallel semantics on a virtual 8-device CPU mesh.

Mirrors the reference's distributed tests (tests/distributed/
_test_distributed.py: N localhost processes, assert per-worker model
equality): here the assertion is that the mesh-sharded grower produces the
IDENTICAL tree to the single-device grower — the psum reproduces the
histogram ReduceScatter + split Allreduce semantics exactly.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from lightgbm_tpu.ops.grower import GrowerParams, grow_tree  # noqa: E402
from lightgbm_tpu.parallel import (  # noqa: E402
    DATA_AXIS,
    l2_gradients,
    make_data_parallel_train_step,
    replicate,
    shard_rows,
)

N, F, MAX_BIN = 512, 6, 16


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(21)
    bins = rng.integers(0, MAX_BIN - 1, size=(N, F), dtype=np.int32)
    label = (bins[:, 0] * 0.3 - bins[:, 1] * 0.1 + rng.normal(size=N)).astype(
        np.float32
    )
    return bins, label


def _single_device_tree(bins, label, params, device):
    # Pin the reference run to the SAME platform as the mesh (CPU): the tree
    # must be identical to the sharded run, and cross-backend f32 reduction
    # order differences can legitimately flip near-tied splits.
    put = lambda x: jax.device_put(x, device)  # noqa: E731
    grad = put(0.0 - np.asarray(label))
    hess = put(np.ones(N, np.float32))
    tree, leaf_id = grow_tree(
        put(np.asarray(bins)),
        grad,
        hess,
        put(np.ones(N, np.float32)),
        put(np.full((F,), MAX_BIN, np.int32)),
        put(np.full((F,), -1, np.int32)),
        put(np.ones((F,), bool)),
        params,
    )
    return tree, leaf_id


def test_sharded_tree_equals_single_device(problem, cpu_mesh_devices):
    bins, label = problem
    params_local = GrowerParams(num_leaves=15, max_bin=MAX_BIN, min_data_in_leaf=5)
    tree_ref, _ = _single_device_tree(bins, label, params_local, cpu_mesh_devices[0])

    mesh = Mesh(np.array(cpu_mesh_devices[:8]), (DATA_AXIS,))
    params_mesh = GrowerParams(
        num_leaves=15, max_bin=MAX_BIN, min_data_in_leaf=5, axis_name=DATA_AXIS
    )
    step = make_data_parallel_train_step(mesh, params_mesh, 0.1, l2_gradients)
    score = shard_rows(np.zeros(N, np.float32), mesh)
    new_score, tree = step(
        shard_rows(bins, mesh),
        shard_rows(label, mesh),
        score,
        replicate(np.full(F, MAX_BIN, np.int32), mesh),
        replicate(np.full(F, -1, np.int32), mesh),
        replicate(np.ones(F, bool), mesh),
    )

    assert int(tree.num_leaves) == int(tree_ref.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(tree.split_feature), np.asarray(tree_ref.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(tree.split_bin), np.asarray(tree_ref.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(tree.leaf_value), np.asarray(tree_ref.leaf_value), rtol=1e-4, atol=1e-5
    )


def test_sharded_score_update_correct(problem, cpu_mesh_devices):
    bins, label = problem
    mesh = Mesh(np.array(cpu_mesh_devices[:8]), (DATA_AXIS,))
    params_mesh = GrowerParams(
        num_leaves=15, max_bin=MAX_BIN, min_data_in_leaf=5, axis_name=DATA_AXIS
    )
    step = make_data_parallel_train_step(mesh, params_mesh, 0.1, l2_gradients)
    score0 = shard_rows(np.zeros(N, np.float32), mesh)
    new_score, tree = step(
        shard_rows(bins, mesh),
        shard_rows(label, mesh),
        score0,
        replicate(np.full(F, MAX_BIN, np.int32), mesh),
        replicate(np.full(F, -1, np.int32), mesh),
        replicate(np.ones(F, bool), mesh),
    )
    # one boosting step on L2 must reduce the loss
    s = np.asarray(new_score)
    assert np.mean((s - label) ** 2) < np.mean(label**2)
    # sharding preserved
    assert "data" in str(new_score.sharding.spec)
