import numpy as np
import pytest

from lightgbm_tpu.binning import BinMapper, MissingType, K_ZERO_THRESHOLD


def test_simple_uniform_binning():
    rng = np.random.default_rng(0)
    vals = rng.uniform(1.0, 2.0, size=10000)
    m = BinMapper.from_sample(vals, max_bin=64)
    assert not m.is_trivial
    assert m.missing_type == MissingType.NONE
    bins = m.values_to_bins(vals)
    assert bins.min() >= 0
    assert bins.max() < m.num_bins
    # roughly equal counts
    counts = np.bincount(bins, minlength=m.num_bins)
    nonzero = counts[counts > 0]
    assert len(nonzero) >= 32
    # monotonicity: larger value -> larger-or-equal bin
    order = np.argsort(vals)
    assert (np.diff(bins[order]) >= 0).all()


def test_bin_boundaries_separate_values():
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0] * 10)
    m = BinMapper.from_sample(vals, max_bin=32, min_data_in_bin=1)
    bins = m.values_to_bins(np.array([1.0, 2.0, 3.0, 4.0, 5.0]))
    # distinct values get distinct bins when budget allows
    assert len(set(bins.tolist())) == 5


def test_zero_gets_own_bin():
    vals = np.concatenate([np.zeros(50), np.linspace(-1, 1, 50)])
    m = BinMapper.from_sample(vals, max_bin=16, min_data_in_bin=1)
    zero_bin = m.values_to_bins(np.array([0.0]))[0]
    neg_bin = m.values_to_bins(np.array([-0.5]))[0]
    pos_bin = m.values_to_bins(np.array([0.5]))[0]
    assert neg_bin < zero_bin < pos_bin


def test_nan_bin():
    vals = np.array([1.0, 2.0, 3.0, np.nan, np.nan, 4.0] * 5)
    m = BinMapper.from_sample(vals, max_bin=16, min_data_in_bin=1)
    assert m.missing_type == MissingType.NAN
    assert m.nan_bin == m.num_bins - 1
    bins = m.values_to_bins(np.array([np.nan, 1.0]))
    assert bins[0] == m.nan_bin
    assert bins[1] != m.nan_bin


def test_no_nan_no_missing_bin():
    vals = np.linspace(0, 1, 100)
    m = BinMapper.from_sample(vals, max_bin=8)
    assert m.missing_type == MissingType.NONE
    assert m.nan_bin == -1


def test_zero_as_missing():
    vals = np.concatenate([np.zeros(50), np.linspace(1, 2, 50)])
    m = BinMapper.from_sample(vals, max_bin=8, zero_as_missing=True)
    assert m.missing_type == MissingType.ZERO
    b = m.values_to_bins(np.array([0.0, np.nan, 1.5]))
    assert b[0] == m.nan_bin
    assert b[1] == m.nan_bin
    assert b[2] != m.nan_bin


def test_trivial_feature():
    vals = np.full(100, 7.0)
    m = BinMapper.from_sample(vals, max_bin=8)
    assert m.is_trivial


def test_categorical_binning_by_frequency():
    vals = np.array([0] * 50 + [1] * 30 + [2] * 20, dtype=np.float64)
    m = BinMapper.from_sample(vals, max_bin=8, is_categorical=True)
    assert m.is_categorical
    bins = m.values_to_bins(np.array([0.0, 1.0, 2.0]))
    # most frequent category -> bin 0
    assert bins[0] == 0
    assert bins[1] == 1
    assert bins[2] == 2
    # unseen category maps to bin 0
    assert m.values_to_bins(np.array([99.0]))[0] == 0


def test_categorical_max_bin_cut():
    rng = np.random.default_rng(1)
    vals = rng.integers(0, 100, size=5000).astype(np.float64)
    m = BinMapper.from_sample(vals, max_bin=16, is_categorical=True)
    assert m.num_bins <= 16


def test_threshold_real_value_roundtrip():
    rng = np.random.default_rng(2)
    vals = rng.normal(size=1000)
    m = BinMapper.from_sample(vals, max_bin=32)
    bins = m.values_to_bins(vals)
    for b in range(m.num_bins - 1):
        thr = m.bin_to_threshold(b)
        left = vals[bins <= b]
        right = vals[(bins > b) & (bins < m.num_bins)]
        if len(left) and len(right):
            assert left.max() <= thr <= right.min()


def test_max_bin_respected():
    rng = np.random.default_rng(3)
    vals = rng.normal(size=100000)
    for mb in (4, 16, 63, 255):
        m = BinMapper.from_sample(vals, max_bin=mb)
        assert m.num_bins <= mb + 1  # +1 for potential nan bin
        assert m.values_to_bins(vals).max() < m.num_bins


def test_forced_bounds_in_mapper():
    """Forced upper bounds land verbatim in the bound list and the budget
    for free bins is spread across the regions between them (reference
    FindBinWithPredefinedBin, bin.cpp:161-244)."""
    rng = np.random.default_rng(9)
    vals = rng.uniform(-10, 10, size=20000)
    m = BinMapper.from_sample(vals, max_bin=32, forced_bounds=[-3.0, 5.5])
    ub = m.bin_upper_bound
    assert -3.0 in ub and 5.5 in ub
    assert len(ub) <= 32 and ub[-1] == np.inf
    # values straddling a forced bound always land in different bins
    lo = m.values_to_bins(np.array([-3.0 - 1e-9]))
    hi = m.values_to_bins(np.array([-3.0 + 1e-6]))
    assert lo[0] < hi[0]
    # free bins still subdivide the regions: far more bins than seeds
    assert len(ub) > 8


def test_forced_bounds_cap_at_max_bin():
    rng = np.random.default_rng(10)
    vals = rng.uniform(0.5, 10, size=5000)
    forced = [float(x) for x in np.linspace(1, 9, 50)]
    m = BinMapper.from_sample(vals, max_bin=8, forced_bounds=forced)
    assert len(m.bin_upper_bound) <= 8
    # first forced bounds win (insertion order, reference bin.cpp:206)
    assert forced[0] in m.bin_upper_bound


def test_forcedbins_file_end_to_end(tmp_path):
    """forcedbins_filename flows from params into the dataset mappers; the
    categorical record is ignored with a warning (dataset_loader.cpp:1447)."""
    import json

    import lightgbm_tpu as lgb

    rng = np.random.default_rng(11)
    X = np.column_stack([
        rng.uniform(-5, 5, size=3000),
        rng.integers(0, 6, size=3000).astype(float),
    ])
    y = (X[:, 0] > 1.25).astype(float) + rng.normal(scale=0.1, size=3000)
    f = tmp_path / "forced.json"
    f.write_text(json.dumps([
        {"feature": 0, "bin_upper_bound": [1.25, 1.25, 2.5]},
        {"feature": 1, "bin_upper_bound": [2.0]},
    ]))
    params = {
        "objective": "regression", "verbosity": -1, "max_bin": 16,
        "forcedbins_filename": str(f), "categorical_feature": [1],
    }
    ds = lgb.Dataset(X, y, params=params, categorical_feature=[1])
    ds.construct()
    ub0 = ds.bin_mappers[0].bin_upper_bound
    assert 1.25 in ub0 and 2.5 in ub0
    assert np.sum(ub0 == 1.25) == 1  # duplicate removed
    assert ds.bin_mappers[1].is_categorical  # record ignored, still cat
    b = lgb.train(params, ds, 5)
    assert np.isfinite(b.predict(X)).all()


def test_forcedbins_malformed_file_ignored(tmp_path):
    """Unparseable forced-bins content warns and is ignored — construct()
    never crashes on it (reference GetForcedBins behavior)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(3)
    X = rng.normal(size=(500, 3))
    y = rng.normal(size=500)
    for content in ("not json[", '{"feature": 0}', '[{"bin_upper_bound": [1]}]'):
        f = tmp_path / "bad.json"
        f.write_text(content)
        p = {"objective": "regression", "verbosity": -1,
             "forcedbins_filename": str(f)}
        ds = lgb.Dataset(X, y, params=p)
        ds.construct()  # must not raise
        assert len(ds.bin_mappers) == 3
