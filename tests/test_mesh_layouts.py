"""Named-mesh layout battery (parallel/mesh.py, ISSUE 13).

Every distributed layout is a MESH SHAPE consumed by the single
``make_mesh_grow`` path — so the contract is uniform and testable on the
8-virtual-device CPU mesh:

* structure parity: data / feature / hybrid specs all reproduce the
  serial tree structure full-dump (the reference's distributed tests
  assert the same across N localhost workers);
* pad math: row padding divides the DATA axis, not the total device
  count (a hybrid (4, 2) mesh pads rows % 4 — the satellite-1 fix);
* overlap: ``overlap_collectives`` splits the frontier histogram psum
  into hist_db0/hist_db1 without changing a byte of the model or a byte
  of the measured collective totals;
* retrace: each layout's grow path traces once and stays warm;
* resume: checkpoints restore onto the same mesh layout byte-identically.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.obs.registry import get_session

# layout name -> the params that select it (everything else identical)
LAYOUTS = {
    "data": {"tree_learner": "data"},
    "feature": {"tree_learner": "feature"},
    "hybrid": {"tree_learner": "data", "mesh_layout": "hybrid"},
}

STRUCT_KEYS = (
    "num_leaves", "split_feature", "threshold", "left_child", "right_child",
)


def _structure(bst):
    """Tree-structure lines of the full dump (config echo excluded)."""
    return "\n".join(
        l for l in bst.model_to_string().splitlines()
        if l.split("=")[0] in STRUCT_KEYS
    )


def _data(n=512, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (
        X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.normal(scale=0.1, size=n) > 0.4
    ).astype(np.float64)
    return X, y


def _params(**over):
    p = dict(
        objective="binary",
        num_leaves=15,
        learning_rate=0.1,
        min_data_in_leaf=5,
        verbosity=-1,
        max_bin=63,
        seed=3,
    )
    p.update(over)
    return p


def _train(X, y, extra, rounds=5):
    p = _params(**extra)
    return lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=rounds)


# ------------------------------------------------------- structure parity
@pytest.mark.parametrize("layout", sorted(LAYOUTS))
def test_layout_structure_parity_vs_serial(layout, cpu_mesh_devices):
    """All three layouts flow through make_mesh_grow and reproduce the
    serial structure, selected ONLY by the spec."""
    X, y = _data()
    serial = _train(X, y, {})
    dist = _train(X, y, LAYOUTS[layout])
    spec = dist._mesh_spec
    assert spec is not None, f"{layout} layout did not form a mesh"
    # the spec IS the layout: a shape, not a code path
    want_shape = {
        "data": (8, 1),      # all devices on the data axis
        "feature": (1, 5),   # largest divisor of the 10 planes <= 8
        "hybrid": (4, 2),    # 8 devices, fd=2 divides devices and planes
    }[layout]
    assert (spec.data, spec.feature) == want_shape
    assert dict(dist._mesh.shape) == {"data": spec.data,
                                      "feature": spec.feature}
    assert _structure(dist) == _structure(serial)
    np.testing.assert_allclose(
        dist.predict(X), serial.predict(X), rtol=1e-4, atol=1e-5
    )


def test_hybrid_pad_rows_from_data_axis(cpu_mesh_devices):
    """Satellite-1 regression: on a (4, 2) hybrid mesh, 994 rows need
    (-994) % 4 == 2 padding rows — deriving the pad from the total device
    count would write 6 and break per-shard row math."""
    X, y = _data(n=994)
    serial = _train(X, y, {})
    dist = _train(X, y, LAYOUTS["hybrid"])
    assert (dist._mesh_spec.data, dist._mesh_spec.feature) == (4, 2)
    assert dist._pad_rows == 2
    assert _structure(dist) == _structure(serial)


# ----------------------------------------------------- collective overlap
def test_overlap_on_off_byte_parity(cpu_mesh_devices):
    """Double-buffered histogram collectives re-order LAUNCHES, not math:
    the model dump (config echo aside) is byte-identical and the measured
    psum byte totals agree exactly — hist_db0 + hist_db1 carry the same
    payload the single hist psum did."""
    ses = get_session()
    X, y = _data(n=640, f=12, seed=1)

    def run(overlap):
        ses.configure(enabled=False)
        ses.reset()
        bst = _train(
            X, y,
            dict(LAYOUTS["data"], leaf_batch=4, telemetry=True,
                 overlap_collectives=overlap),
            rounds=4,
        )
        tel = bst.telemetry()
        meas = sum(
            e["collective_measured"]["psum_bytes"]
            for e in tel["events"] if e["event"] == "iteration"
        )
        ses.configure(enabled=False)
        ses.reset()
        return bst, meas

    try:
        off, meas_off = run("off")
        on, meas_on = run("on")
    finally:
        ses.configure(enabled=False)
        ses.reset()
    assert off._grower_params.overlap_collectives is False
    assert on._grower_params.overlap_collectives is True
    strip = lambda b: "\n".join(
        l for l in b.model_to_string().splitlines()
        if "overlap_collectives" not in l
    )
    assert strip(on) == strip(off)
    assert meas_on == meas_off > 0


def test_overlap_auto_stays_off_at_leaf_batch_one(cpu_mesh_devices):
    """auto gating: leaf_batch=1's serial loop has nothing to overlap
    with, so the trace keeps its pre-overlap key (no retrace risk)."""
    X, y = _data()
    bst = _train(X, y, LAYOUTS["data"])
    assert bst._grower_params.overlap_collectives is False


# ----------------------------------------------------------- retrace guard
@pytest.mark.parametrize("layout", sorted(LAYOUTS))
def test_zero_retrace_after_warmup(layout, cpu_mesh_devices):
    """Each layout's grow path compiles during warmup and never again for
    identical shapes — the perf contract's retrace invariant, per spec.
    (The label counts per-booster: every booster builds its own shard_map
    closure, so the warm check continues the SAME booster.)"""
    X, y = _data()
    bst = _train(X, y, LAYOUTS[layout], rounds=3)  # warmup
    warm = dict(lgb.compile_counts_by_label())
    for _ in range(3):
        bst.update()
    assert dict(lgb.compile_counts_by_label()) == warm, (
        f"{layout} layout retraced after warmup"
    )


# ------------------------------------------------------------ kill/resume
def test_checkpoint_resume_under_mesh_layout(tmp_path, cpu_mesh_devices):
    """Kill-and-resume under a mesh spec: the resumed run re-forms the
    same layout from config and continues byte-identically."""
    X, y = _data()
    ckdir = str(tmp_path / "ck")
    p = _params(
        checkpoint_dir=ckdir, checkpoint_interval=4, deterministic=True,
        **LAYOUTS["data"],
    )

    baseline = lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=10)
    ref = baseline.model_to_string()

    lgb.train(p, lgb.Dataset(X, y, params=p), num_boost_round=8)  # "killed"
    resumed = lgb.train(
        p, lgb.Dataset(X, y, params=p), num_boost_round=10,
        resume_from=ckdir,
    )
    assert resumed._mesh_spec == baseline._mesh_spec
    assert (resumed._mesh_spec.data, resumed._mesh_spec.feature) == (8, 1)
    assert resumed.current_iteration() == 10
    assert resumed.model_to_string() == ref
