"""Native (C++/OpenMP) binning vs the NumPy path — exact parity across
missing-type modes (native/binning.cpp; reference DenseBin::Push analog)."""

import ctypes

import numpy as np
import pytest

from lightgbm_tpu.binning import K_ZERO_THRESHOLD, BinMapper
from lightgbm_tpu.native import load_native


@pytest.fixture(scope="module")
def lib():
    lib = load_native()
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def _native_bins(lib, mapper: BinMapper, values: np.ndarray) -> np.ndarray:
    vals = np.ascontiguousarray(values, dtype=np.float64)
    ub = np.ascontiguousarray(mapper.bin_upper_bound, dtype=np.float64)
    out = np.empty(len(vals), dtype=np.int32)
    lib.bin_numeric_f64(
        vals.ctypes.data,
        len(vals),
        ub.ctypes.data,
        len(ub),
        int(mapper.missing_type),
        int(mapper.nan_bin),
        K_ZERO_THRESHOLD,
        out.ctypes.data,
    )
    return out


@pytest.mark.parametrize("zero_as_missing", [False, True])
@pytest.mark.parametrize("with_nan", [False, True])
def test_native_matches_numpy(lib, zero_as_missing, with_nan):
    rng = np.random.default_rng(int(zero_as_missing) * 2 + int(with_nan))
    vals = rng.normal(size=200_000)
    vals[rng.random(len(vals)) < 0.1] = 0.0
    if with_nan:
        vals[rng.random(len(vals)) < 0.05] = np.nan
    m = BinMapper.from_sample(
        vals[:50_000], 255, zero_as_missing=zero_as_missing
    )
    # the GENUINE NumPy fallback (native path disabled), not a re-derivation
    orig = BinMapper._values_to_bins_native
    BinMapper._values_to_bins_native = lambda self, values: None
    try:
        want = m.values_to_bins(vals)
    finally:
        BinMapper._values_to_bins_native = orig
    got = _native_bins(lib, m, vals)
    np.testing.assert_array_equal(got, want)


def test_native_handles_extremes(lib):
    m = BinMapper.from_sample(np.linspace(-5, 5, 1000), 16)
    vals = np.array([-np.inf, np.inf, -1e300, 1e300, 0.0, np.nan])
    got = _native_bins(lib, m, vals)
    want = m.values_to_bins(vals)
    np.testing.assert_array_equal(got, want)


def test_native_greedy_find_bin_matches_python():
    """native/binning.cpp greedy_find_bin must be operation-identical to
    the Python fallback (reference GreedyFindBin, src/io/bin.cpp)."""
    import lightgbm_tpu.binning as B
    import lightgbm_tpu.native.build as nb

    if nb.load_native() is None:
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(3)
    for _ in range(10):
        n = int(rng.integers(4097, 40000))
        dv = np.unique(np.sort(rng.normal(size=n)))
        cnt = rng.integers(1, 50, size=len(dv)).astype(np.int64)
        cnt[rng.integers(0, len(dv), 4)] += int(rng.integers(1000, 20000))
        total = int(cnt.sum())
        # 8192 > n exercises the native n <= max_bin branch too
        mb = int(rng.choice([63, 255, 1024, 8192 + 60000]))
        got = B._greedy_find_bin(dv, cnt, mb, total, 3)
        saved = (nb._tried, nb._lib)
        nb._tried, nb._lib = True, None  # force the Python fallback
        try:
            exp = B._greedy_find_bin(dv, cnt, mb, total, 3)
        finally:
            nb._tried, nb._lib = saved
        assert got == exp
