"""Native (real-TPU) parity tier — `LGBM_TPU_NATIVE=1 pytest -m native_tpu`.

Hardware presence auto-expands the suite with the escrowed-kernel parity
checks that tools/perf_r4.py runs standalone: the streaming partition
kernel (both entry modes), the bf16/int8/u16-wide seg histograms, and the
forest-walk predictor, each against its XLA oracle on the attached chip.
Off-TPU these are skipped (conftest), and the deviceless Mosaic compile
coverage lives in test_aot_mosaic.py.
"""

import importlib.util
import os
import sys

import pytest

pytestmark = pytest.mark.native_tpu

_TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _load_perf_r4():
    if _TOOLS not in sys.path:
        sys.path.insert(0, _TOOLS)
    spec = importlib.util.spec_from_file_location(
        "perf_r4", os.path.join(_TOOLS, "perf_r4.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_escrowed_kernels_native_parity():
    """Partition (column + bits-fed), seg-hist (bf16 + int8), forest walk —
    all bit/tolerance-checked against their oracles on the real chip."""
    _load_perf_r4().parity_native()


def test_wide_seg_hist_native():
    """u16 wide planes (max_bin > 256) on the real chip vs the oracle."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops.histogram import leaf_histogram_segment
    from lightgbm_tpu.ops.pallas.seg import (
        pack_rows, padded_rows, seg_hist_pallas, unpack_stats,
    )

    rng = np.random.default_rng(3)
    n, f, b = 50_000, 4, 1024
    n_pad = padded_rows(n)
    bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = jax.device_put(
        pack_rows(jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h),
                  jnp.asarray(m), n_pad, wide=True)
    )
    hs = seg_hist_pallas(
        seg, jnp.asarray([137, 40_000], jnp.int32), f=f, num_bins=b,
        n_pad=n_pad, wide=True,
    )
    bo, go, ho, mo, _ = unpack_stats(seg[:, 137:137 + 40_000], f, wide=True)
    ref = leaf_histogram_segment(bo, go, ho, mo, b)
    rel = float(
        np.abs(np.asarray(hs) - np.asarray(ref)).max()
        / max(1e-9, np.abs(np.asarray(ref)).max())
    )
    assert rel < 5e-6, rel
