"""Deviceless Mosaic compile checks for every flagship Pallas kernel.

Rounds 3-4 shipped TPU-gated kernels the Mosaic compiler had never seen
(the tunnel was down both rounds); the first tunnel-up moment found four
distinct lowering rejections (value dynamic_slice, f32 tpu.iota, i1
relayout/select, i1-result scf.if).  These tests pin the fix: libtpu's
compiler runs fine WITHOUT hardware via a topology descriptor, so every
kernel must AOT-compile against a v5e topology in plain CPU CI.

The kernel registry lives in tools/aot_check.py (also runnable standalone
for debugging: ``python tools/aot_check.py [filter]``).
"""

import importlib.util
import os

import pytest

pytestmark = pytest.mark.slow  # ~20-60 s/kernel cold; cached on re-runs

_SPEC = importlib.util.spec_from_file_location(
    "aot_check",
    os.path.join(os.path.dirname(__file__), "..", "tools", "aot_check.py"),
)
aot_check = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(aot_check)


@pytest.fixture(scope="module")
def topo():
    try:
        return aot_check._topo()
    except Exception as e:  # no local libtpu — nothing to check against
        pytest.skip(f"no deviceless TPU topology available: {e}")


@pytest.mark.parametrize("name", sorted(aot_check.CHECKS))
def test_kernel_mosaic_compiles(topo, name):
    compiled = aot_check.CHECKS[name](topo)
    assert compiled is not None
