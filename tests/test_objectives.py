"""Objective gradient/hessian parity vs NumPy oracles.

Mirrors the reference's objective math (src/objective/*.hpp); each case
cross-checks get_gradients against a direct NumPy transcription.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.config import Config  # noqa: E402
from lightgbm_tpu.objectives import create_objective  # noqa: E402

N = 64


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    score = rng.normal(size=N).astype(np.float32)
    label = rng.normal(size=N)
    weight = rng.uniform(0.5, 2.0, size=N)
    return score, label, weight


def _grads(obj_name, score, label, weight=None, extra=None):
    params = {"objective": obj_name}
    params.update(extra or {})
    cfg = Config.from_params(params)
    obj = create_objective(cfg)
    obj.init(label, weight)
    g, h = obj.get_gradients(jnp.asarray(score)[None])
    return np.asarray(g[0], dtype=np.float64), np.asarray(h[0], dtype=np.float64), obj


def test_l2(data):
    score, label, weight = data
    g, h, _ = _grads("regression", score, label)
    np.testing.assert_allclose(g, score - label, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(h, np.ones(N))


def test_l2_weighted(data):
    score, label, weight = data
    g, h, _ = _grads("regression", score, label, weight)
    np.testing.assert_allclose(g, (score - label) * weight, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h, weight, rtol=1e-5)


def test_l1(data):
    score, label, _ = data
    g, h, _ = _grads("regression_l1", score, label)
    np.testing.assert_allclose(g, np.sign(score - label), atol=1e-6)


def test_huber(data):
    score, label, _ = data
    g, h, _ = _grads("huber", score, label, extra={"alpha": 0.5})
    diff = score.astype(np.float64) - label
    expect = np.where(np.abs(diff) <= 0.5, diff, 0.5 * np.sign(diff))
    np.testing.assert_allclose(g, expect, rtol=1e-4, atol=1e-5)


def test_fair(data):
    score, label, _ = data
    g, h, _ = _grads("fair", score, label, extra={"fair_c": 1.0})
    x = score.astype(np.float64) - label
    np.testing.assert_allclose(g, x / (np.abs(x) + 1.0), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h, 1.0 / (np.abs(x) + 1.0) ** 2, rtol=1e-4, atol=1e-5)


def test_poisson(data):
    score, label, _ = data
    pos_label = np.abs(label) + 0.1
    g, h, obj = _grads("poisson", score, pos_label)
    es = np.exp(score.astype(np.float64))
    np.testing.assert_allclose(g, es - pos_label, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(h, es * np.exp(0.7), rtol=1e-4, atol=1e-4)
    # boost-from-score is log of the mean
    assert obj.boost_from_score() == pytest.approx(np.log(pos_label.mean()), rel=1e-6)


def test_quantile(data):
    score, label, _ = data
    g, h, _ = _grads("quantile", score, label, extra={"alpha": 0.3})
    delta = score.astype(np.float64) - label
    expect = np.where(delta >= 0, 0.7, -0.3)
    np.testing.assert_allclose(g, expect, rtol=1e-5, atol=1e-6)


def test_gamma_tweedie(data):
    score, label, _ = data
    pos_label = np.abs(label) + 0.1
    g, h, _ = _grads("gamma", score, pos_label)
    en = np.exp(-score.astype(np.float64))
    np.testing.assert_allclose(g, 1.0 - pos_label * en, rtol=1e-4, atol=1e-4)
    g2, h2, _ = _grads("tweedie", score, pos_label, extra={"tweedie_variance_power": 1.3})
    s = score.astype(np.float64)
    e1, e2 = np.exp(-0.3 * s), np.exp(0.7 * s)
    np.testing.assert_allclose(g2, -pos_label * e1 + e2, rtol=1e-3, atol=1e-3)


def test_binary(data):
    score, _, _ = data
    y01 = (np.random.default_rng(3).random(N) > 0.5).astype(np.float64)
    g, h, obj = _grads("binary", score, y01)
    yy = np.where(y01 > 0, 1.0, -1.0)
    resp = -yy / (1.0 + np.exp(yy * score.astype(np.float64)))
    np.testing.assert_allclose(g, resp, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h, np.abs(resp) * (1.0 - np.abs(resp)), rtol=1e-4, atol=1e-5)
    p = y01.mean()
    assert obj.boost_from_score() == pytest.approx(np.log(p / (1 - p)), rel=1e-6)


def test_multiclass_softmax():
    rng = np.random.default_rng(5)
    k, n = 3, 32
    score = rng.normal(size=(k, n)).astype(np.float32)
    label = rng.integers(0, k, size=n).astype(np.float64)
    cfg = Config.from_params({"objective": "multiclass", "num_class": k})
    obj = create_objective(cfg)
    obj.init(label, None)
    g, h = obj.get_gradients(jnp.asarray(score))
    sm = np.exp(score) / np.exp(score).sum(axis=0, keepdims=True)
    onehot = np.eye(k)[label.astype(int)].T
    np.testing.assert_allclose(np.asarray(g), sm - onehot, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(h), (k / (k - 1.0)) * sm * (1 - sm), rtol=1e-4, atol=1e-5
    )


def test_lambdarank_directions():
    # higher-labeled items must get negative gradients (pushed up)
    n_q, qs = 4, 8
    rng = np.random.default_rng(11)
    label = np.tile(np.arange(qs) % 4, n_q).astype(np.float64)
    score = rng.normal(size=n_q * qs).astype(np.float32)
    cfg = Config.from_params({"objective": "lambdarank"})
    obj = create_objective(cfg)
    obj.init(label, None, query_boundaries=np.arange(0, (n_q + 1) * qs, qs))
    g, h = obj.get_gradients(jnp.asarray(score)[None])
    g = np.asarray(g[0])
    h = np.asarray(h[0])
    assert np.all(h >= -1e-6)
    # per query, mean gradient of top-label items < mean of bottom-label items
    for q in range(n_q):
        seg = slice(q * qs, (q + 1) * qs)
        gl, ll = g[seg], label[seg]
        assert gl[ll == 3].mean() < gl[ll == 0].mean()


def test_xendcg_zero_sum():
    n_q, qs = 3, 8
    rng = np.random.default_rng(13)
    label = rng.integers(0, 4, size=n_q * qs).astype(np.float64)
    score = rng.normal(size=n_q * qs).astype(np.float32)
    cfg = Config.from_params({"objective": "rank_xendcg"})
    obj = create_objective(cfg)
    obj.init(label, None, query_boundaries=np.arange(0, (n_q + 1) * qs, qs))
    g, h = obj.get_gradients(jnp.asarray(score)[None], jax.random.PRNGKey(0))
    g = np.asarray(g[0]).reshape(n_q, qs)
    # per-query lambdas approximately sum to zero (gradient of a softmax loss)
    np.testing.assert_allclose(g.sum(axis=1), 0.0, atol=1e-4)


def test_renew_tree_output_median():
    rng = np.random.default_rng(17)
    label = rng.normal(size=40)
    score = np.zeros(40)
    leaf_id = np.repeat([0, 1], 20)
    cfg = Config.from_params({"objective": "regression_l1"})
    obj = create_objective(cfg)
    obj.init(label, None)
    out = obj.renew_tree_output(score, leaf_id, np.zeros(2), None)
    assert out[0] == pytest.approx(np.median(label[:20]), abs=1e-9)
    assert out[1] == pytest.approx(np.median(label[20:]), abs=1e-9)
