"""Refit + snapshot_freq (reference: GBDT::RefitTree gbdt.cpp:266,
SerialTreeLearner::FitByExistingTree serial_tree_learner.cpp:250,
GBDT::Train snapshot loop gbdt.cpp:258)."""

import glob
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 5))
    y = X[:, 0] * 2 + rng.normal(scale=0.1, size=600)
    b = lgb.train(
        {"objective": "regression", "verbosity": -1, "num_leaves": 7},
        lgb.Dataset(X, y),
        7,
    )
    return b, X, y


def test_refit_improves_on_shifted_data(trained):
    b, X, y = trained
    rng = np.random.default_rng(1)
    X2 = rng.normal(size=(600, 5))
    y2 = X2[:, 0] * 2 + 1.0 + rng.normal(scale=0.1, size=600)
    b2 = b.refit(X2, y2, decay_rate=0.5)
    assert np.mean((b2.predict(X2) - y2) ** 2) < np.mean(
        (b.predict(X2) - y2) ** 2
    )
    # structure is preserved: same leaves, same split features
    assert [t.num_leaves for t in b2.models_] == [t.num_leaves for t in b.models_]
    for t1, t2 in zip(b.models_, b2.models_):
        np.testing.assert_array_equal(t1.split_feature, t2.split_feature)
        np.testing.assert_allclose(t1.threshold, t2.threshold)


def test_refit_decay_one_is_identity(trained):
    b, X, y = trained
    rng = np.random.default_rng(2)
    X2 = rng.normal(size=(600, 5))
    y2 = X2[:, 0] + rng.normal(size=600)
    b2 = b.refit(X2, y2, decay_rate=1.0)
    np.testing.assert_allclose(b2.predict(X2), b.predict(X2), atol=1e-7)


def test_snapshot_freq(tmp_path):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 4))
    y = X[:, 0] + rng.normal(scale=0.1, size=400)
    out = str(tmp_path / "m.txt")
    b = lgb.train(
        {
            "objective": "regression",
            "verbosity": -1,
            "num_leaves": 7,
            "snapshot_freq": 2,
            "output_model": out,
        },
        lgb.Dataset(X, y),
        5,
    )
    snaps = sorted(glob.glob(out + ".snapshot_iter_*"))
    assert [os.path.basename(s) for s in snaps] == [
        "m.txt.snapshot_iter_2",
        "m.txt.snapshot_iter_4",
    ]
    # a snapshot is a loadable model with fewer trees
    snap = lgb.Booster(model_file=snaps[0])
    assert snap.num_trees() == 2
    assert np.isfinite(snap.predict(X)).all()
