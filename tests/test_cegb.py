"""Cost-Effective Gradient Boosting (reference:
cost_effective_gradient_boosting.hpp — DeltaGain's split and coupled
penalties; the coupled penalty applies until a feature is first used
anywhere in the model)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402


@pytest.fixture()
def xy():
    rng = np.random.default_rng(0)
    n = 1000
    X = rng.normal(size=(n, 2))
    # feature 0 slightly stronger than feature 1 (correlated targets)
    sig = X[:, 0] * 1.0 + X[:, 1] * 0.9
    y = sig + rng.normal(scale=0.1, size=n)
    return X, y


def test_coupled_penalty_steers_feature_choice(xy):
    X, y = xy
    base = {"objective": "regression", "num_leaves": 4, "verbosity": -1}
    free = lgb.train(base, lgb.Dataset(X, y), 1)
    assert free.models_[0].split_feature[0] == 0
    # a big acquisition cost on feature 0 makes feature 1 win the root
    pen = lgb.train(
        {**base, "cegb_tradeoff": 1.0,
         "cegb_penalty_feature_coupled": [1e6, 0.0]},
        lgb.Dataset(X, y),
        1,
    )
    assert pen.models_[0].split_feature[0] == 1


def test_huge_coupled_penalty_blocks_feature_entirely(xy):
    X, y = xy
    b = lgb.train(
        {
            "objective": "regression",
            "num_leaves": 8,
            "verbosity": -1,
            "cegb_tradeoff": 1.0,
            "cegb_penalty_feature_coupled": [1e9, 0.0],
        },
        lgb.Dataset(X, y),
        8,
    )
    feats = {int(f) for t in b.models_ for f in t.split_feature[: t.num_leaves - 1]}
    assert feats == {1}


def test_coupled_penalty_paid_once_unlocks_feature():
    """Once a feature is bought its later splits are free — same tree
    included (reference UpdateLeafBestSplits unlocks cached candidates).
    Single feature, penalty below the root gain but above deep-node gains:
    the tree must still grow past the root."""
    rng = np.random.default_rng(1)
    n = 2000
    X = rng.normal(size=(n, 1))
    y = np.sign(X[:, 0]) * 2.0 + 0.3 * X[:, 0] + rng.normal(scale=0.1, size=n)
    base = {
        "objective": "regression",
        "num_leaves": 16,
        "min_data_in_leaf": 5,
        "verbosity": -1,
    }
    free = lgb.train(base, lgb.Dataset(X, y), 1)
    # root gain ~ n * var_reduction (thousands); deep gains are far smaller
    pen = lgb.train(
        {**base, "cegb_tradeoff": 1.0,
         "cegb_penalty_feature_coupled": [500.0]},
        lgb.Dataset(X, y),
        1,
    )
    assert free.models_[0].num_leaves > 2
    assert pen.models_[0].num_leaves == free.models_[0].num_leaves


def test_split_penalty_prunes_growth(xy):
    X, y = xy
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    free = lgb.train(base, lgb.Dataset(X, y), 1)
    pen = lgb.train(
        {**base, "cegb_tradeoff": 1.0, "cegb_penalty_split": 0.5},
        lgb.Dataset(X, y),
        1,
    )
    assert pen.models_[0].num_leaves < free.models_[0].num_leaves
