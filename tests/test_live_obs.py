"""Live ops plane: flight recorder, health watchdog, metrics exporter.

Covers the always-on ring buffer (bounds/eviction/atomic dumps), the
per-iteration alert rules against synthetic telemetry, the Prometheus
text exporter (schema + line format + HTTP endpoint), the chaos-drill
fault dumps, and the zero-retrace contract for the whole plane.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs.export import (  # noqa: E402
    MetricsExporter,
    health_snapshot,
    prometheus_snapshot,
    sanitize_metric_name,
)
from lightgbm_tpu.obs.flight import (  # noqa: E402
    FLIGHT_SCHEMA,
    MIN_CAPACITY,
    FlightRecorder,
    get_flight,
    list_flight_dumps,
)
from lightgbm_tpu.obs.health import (  # noqa: E402
    SEV_CRITICAL,
    SEV_WARN,
    HealthWatchdog,
)
from lightgbm_tpu.obs.registry import TelemetrySession, get_session  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_obs():
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    flight = get_flight()
    flight.reset()
    flight.configure(fault_dir="", run_info={}, active=True)
    yield
    ses.configure(enabled=False)
    ses.reset()
    flight.reset()
    flight.configure(fault_dir="", run_info={}, active=True)


def _data(n=400, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


def _iter_event(i, wall=10.0, **extra):
    e = {"event": "iteration", "iter": i, "wall_ms": wall}
    e.update(extra)
    return e


# ------------------------------------------------------------- flight ring
def test_ring_bounds_and_eviction():
    fr = FlightRecorder(capacity=40)
    for i in range(100):
        fr.note_event(_iter_event(i))
    events = fr.events()
    assert len(events) == 40
    assert events[0]["iter"] == 60  # oldest 60 evicted
    assert events[-1]["iter"] == 99


def test_ring_capacity_floor_and_reconfigure():
    fr = FlightRecorder(capacity=1)
    assert fr.capacity == MIN_CAPACITY
    fr.configure(capacity=64)
    assert fr.capacity == 64
    for i in range(10):
        fr.note_event(_iter_event(i))
    fr.configure(capacity=48)  # reconfigure keeps buffered events
    assert [e["iter"] for e in fr.events()] == list(range(10))


def test_alert_history_survives_event_burst():
    fr = FlightRecorder(capacity=32)
    alert = {"event": "alert", "rule": "hbm", "severity": SEV_WARN, "iter": 3}
    fr.note_alert(alert)
    for i in range(500):
        fr.note_event(_iter_event(i))
    # the alert was evicted from the event ring by the burst...
    assert all(e.get("event") != "alert" for e in fr.events())
    # ...but the dedicated alert history still has it for the dump
    assert fr.alerts() == [alert]


def test_inactive_recorder_records_nothing(tmp_path):
    fr = FlightRecorder()
    fr.configure(fault_dir=str(tmp_path), active=False)
    fr.note_event(_iter_event(0))
    assert fr.events() == []
    assert fr.dump("test") == ""
    assert list_flight_dumps(str(tmp_path)) == []


# ------------------------------------------------------------ atomic dumps
def test_dump_atomicity_and_schema(tmp_path):
    fr = FlightRecorder(capacity=64)
    fr.configure(
        fault_dir=str(tmp_path), run_info={"objective": "regression"}
    )
    for i in range(50):
        fr.note_event(_iter_event(i))
    fr.note_alert(
        {"event": "alert", "rule": "numerics", "severity": SEV_CRITICAL,
         "iter": 49, "message": "boom"}
    )
    fr.note_checkpoint(str(tmp_path / "ckpt_iter_00000048.pkl"))
    path = fr.dump("numerics_test")
    assert os.path.isfile(path)
    # tmp+rename: no stray temp files next to the dump
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
    with open(path) as f:
        doc = json.load(f)
    assert doc["schema"] == FLIGHT_SCHEMA
    assert doc["reason"] == "numerics_test"
    assert doc["run_info"] == {"objective": "regression"}
    assert doc["last_checkpoint"].endswith("ckpt_iter_00000048.pkl")
    iters = [e for e in doc["events"] if e["event"] == "iteration"]
    assert len(iters) >= MIN_CAPACITY
    assert doc["alerts"][-1]["rule"] == "numerics"
    assert doc["n_events"] == len(doc["events"])
    # second dump gets a distinct filename even within the same second
    path2 = fr.dump("numerics_test")
    assert path2 != path
    assert list_flight_dumps(str(tmp_path)) == [path, path2]


def test_dump_without_directory_is_silent_noop():
    fr = FlightRecorder()
    fr.note_event(_iter_event(0))
    assert fr.dump("whatever") == ""


# --------------------------------------------------------- watchdog rules
def _warm_watchdog(wd, ses, n=None, wall=10.0):
    n = wd.warmup_iters + 2 if n is None else n
    alerts = []
    for i in range(n):
        alerts += wd.observe(_iter_event(i, wall=wall), ses)
    return n


def _fresh_session():
    ses = TelemetrySession()
    ses.configure(enabled=True)
    return ses


def test_throughput_rule_and_compile_exclusion():
    ses = _fresh_session()
    wd = HealthWatchdog()
    n = _warm_watchdog(wd, ses, wall=10.0)
    # a compile iteration's wall spike is NOT a regression
    out = wd.observe(_iter_event(n, wall=500.0, compiles_delta=2), ses)
    assert out == []
    out = wd.observe(_iter_event(n + 1, wall=500.0), ses)
    assert [a["rule"] for a in out] == ["throughput"]
    assert out[0]["severity"] == SEV_WARN
    assert out[0]["value"] == 500.0
    assert ses.counters["alerts_total"] == 1
    assert ses.counters["alerts/throughput"] == 1


def test_rule_cooldown_suppresses_repeat_alerts():
    ses = _fresh_session()
    wd = HealthWatchdog(cooldown_iters=10)
    n = _warm_watchdog(wd, ses, wall=10.0)
    assert wd.observe(_iter_event(n, wall=900.0), ses)
    # persistently slow: within the cooldown window nothing new fires
    fired = []
    for i in range(n + 1, n + 8):
        fired += wd.observe(_iter_event(i, wall=900.0), ses)
    assert fired == []
    assert wd.alerts_emitted == 1
    # the remembered alert tracked the reading while the rule stayed armed
    # (at n+1 the wall still beat the bound; after that the EMA absorbed
    # the sustained level, which is exactly the regression-not-new-normal
    # semantics the EMA gives us)
    assert wd.active_alerts()[0]["iter"] == n + 1


def test_numerics_rule_is_critical_and_skips_warmup():
    ses = _fresh_session()
    wd = HealthWatchdog()
    ses.inc("numerics/guard_trips")
    out = wd.observe(_iter_event(0), ses)
    assert [a["rule"] for a in out] == ["numerics"]
    assert out[0]["severity"] == SEV_CRITICAL
    assert wd.status() == "critical"
    # same trip count -> no re-alert
    assert wd.observe(_iter_event(1), ses) == []


def test_commit_rate_rule_requires_batched_growth():
    ses = _fresh_session()
    wd = HealthWatchdog(commit_rate_floor=0.25)
    ses.set_gauge("grower.commit_rate", 0.1)
    ses.set_gauge("grower.leaf_batch_effective", 1.0)
    n = _warm_watchdog(wd, ses)
    assert wd.active_alerts() == []  # K=1: rule disarmed
    ses.set_gauge("grower.leaf_batch_effective", 4.0)
    out = wd.observe(_iter_event(n), ses)
    assert [a["rule"] for a in out] == ["commit_rate"]


def test_refine_rate_rule_requires_int8_engaged():
    ses = _fresh_session()
    wd = HealthWatchdog(refine_rate_ceiling=0.5)
    ses.set_gauge("hist/near_tie_refine_rate", 0.9)
    n = _warm_watchdog(wd, ses)
    assert wd.active_alerts() == []  # not engaged: rule disarmed
    ses.set_gauge("hist/int8_engaged", 1.0)
    out = wd.observe(_iter_event(n), ses)
    assert [a["rule"] for a in out] == ["refine_rate"]


def test_straggler_and_hbm_rules():
    ses = _fresh_session()
    wd = HealthWatchdog(
        straggler_skew_ceiling=1.5,
        hbm_growth_factor=1.5,
        hbm_growth_floor_bytes=1024,
    )
    ses.set_gauge("memory/hbm_bytes_in_use", 1e6)
    n = _warm_watchdog(wd, ses)
    assert wd.active_alerts() == []
    ses.set_gauge("straggler/skew", 2.0)
    ses.set_gauge("memory/hbm_bytes_in_use", 1e6 * 1.6)
    out = wd.observe(_iter_event(n), ses)
    assert sorted(a["rule"] for a in out) == ["hbm", "straggler"]
    assert wd.status() == "warn"


def test_alerts_expire_from_active_window():
    ses = _fresh_session()
    wd = HealthWatchdog(activity_window=5, cooldown_iters=3)
    ses.inc("numerics/guard_trips")
    wd.observe(_iter_event(0), ses)
    assert wd.status() == "critical"
    for i in range(1, 10):
        wd.observe(_iter_event(i), ses)
    assert wd.active_alerts() == []
    assert wd.status() == "ok"


def test_note_fault_registers_active_alert_without_observe():
    ses = _fresh_session()
    ses.inc("numerics/guard_trips")
    wd = HealthWatchdog()
    wd.note_fault("numerics", 7, "gradient non-finite", ses=ses)
    assert wd.status() == "critical"
    assert wd.active_alerts()[0]["message"] == "gradient non-finite"
    # the watermark synced: a later observe doesn't double-alert
    assert wd.observe(_iter_event(8), ses) == []


def test_record_alert_preserves_deferred_iteration_line(tmp_path):
    sink = str(tmp_path / "events.jsonl")
    ses = TelemetrySession()
    ses.configure(enabled=True, sink_path=sink)
    ses.record({"event": "iteration", "iter": 0}, defer=True)
    ses.record_alert({"event": "alert", "rule": "hbm", "iter": 0})
    ses.annotate_last({"eval": {"t": {"l2": 1.0}}})
    ses.close()
    lines = [json.loads(x) for x in open(sink)]
    assert [e["event"] for e in lines] == ["alert", "iteration"]
    # the late eval annotation landed on the iteration, not the alert
    assert lines[1]["eval"] == {"t": {"l2": 1.0}}
    assert "eval" not in lines[0]
    assert [e["event"] for e in ses.events] == ["alert", "iteration"]


# ------------------------------------------------------------- exporter
def test_sanitize_metric_name():
    assert sanitize_metric_name("hist/near_tie_refines") == (
        "lgbtpu_hist_near_tie_refines"
    )
    assert sanitize_metric_name("grower.commit_rate") == (
        "lgbtpu_grower_commit_rate"
    )
    assert sanitize_metric_name("9lives") == "lgbtpu__9lives"
    assert sanitize_metric_name("a//b..c") == "lgbtpu_a_b_c"


def test_prometheus_snapshot_format():
    ses = get_session()
    ses.configure(enabled=True)
    ses.inc("iterations", 5)
    ses.inc("hist/near_tie_refines_total", 3)
    ses.set_gauge("grower.commit_rate", 0.75)
    ses.set_gauge("hist/int8_engaged", 1.0)
    wd = HealthWatchdog()
    wd.note_fault("numerics", 4, "boom", ses=ses)
    text = prometheus_snapshot(ses, health=health_snapshot(wd, ses))
    lines = text.strip().splitlines()
    import re

    sample_re = re.compile(
        r"^[a-zA-Z_][a-zA-Z0-9_]*(\{[^}]*\})? -?[0-9][0-9.e+-]*$"
    )
    samples = [ln for ln in lines if not ln.startswith("#")]
    assert samples, text
    for ln in samples:
        assert sample_re.match(ln), f"bad exposition line: {ln!r}"
        assert ln.startswith("lgbtpu_"), ln
    by_name = {ln.split(" ")[0]: ln.rsplit(" ", 1)[1] for ln in samples}
    assert by_name["lgbtpu_up"] == "1"
    assert by_name["lgbtpu_iterations_total"] == "5"
    assert by_name["lgbtpu_grower_commit_rate"] == "0.75"
    assert by_name["lgbtpu_health_status"] == "2"
    assert (
        'lgbtpu_alert_active{rule="numerics",severity="critical"}' in by_name
    )
    # every sample has a TYPE line; counters carry the _total suffix
    typed = {
        ln.split(" ")[2] for ln in lines if ln.startswith("# TYPE ")
    }
    for name in by_name:
        assert name.split("{")[0] in typed, name
    assert "lgbtpu_iterations_total" in typed


def test_health_snapshot_schema():
    ses = get_session()
    ses.configure(enabled=True)
    ses.inc("iterations", 3)
    wd = HealthWatchdog()
    doc = health_snapshot(wd, ses)
    assert doc["schema"] == "lgbtpu.health.v1"
    assert doc["status"] == "ok"
    assert doc["iter"] == 3
    assert doc["alerts"] == []
    assert set(doc["flight"]) == {
        "capacity", "n_events", "last_dump", "last_trace_dump",
        "last_checkpoint",
    }
    assert set(doc["trace"]) >= {"active", "ring", "spans_total"}
    json.dumps(doc)  # JSON-serializable end to end


def test_exporter_http_endpoint():
    ses = get_session()
    ses.configure(enabled=True)
    ses.inc("iterations", 2)
    exporter = MetricsExporter(0)  # ephemeral port
    try:
        port = exporter.start()
        assert port > 0 and exporter.url
        body = urllib.request.urlopen(
            exporter.url + "/metrics", timeout=5
        ).read().decode()
        assert "lgbtpu_iterations_total 2" in body
        health = json.loads(
            urllib.request.urlopen(
                exporter.url + "/healthz", timeout=5
            ).read()
        )
        assert health["schema"] == "lgbtpu.health.v1"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(exporter.url + "/nope", timeout=5)
    finally:
        exporter.stop()
    assert exporter.port == 0  # stopped


# -------------------------------------------------- end-to-end fault paths
def test_chaos_drill_numerics_flight_dump(tmp_path):
    from lightgbm_tpu.resilience import chaos

    path = chaos.flight_dump_drill_numerics(str(tmp_path))
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"].startswith("numerics")
    assert any(a["rule"] == "numerics" for a in doc["alerts"])


def test_chaos_drill_degradation_flight_dump(tmp_path):
    from lightgbm_tpu.resilience import chaos

    path = chaos.flight_dump_drill_degradation(str(tmp_path))
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "degradation"
    assert any(e.get("event") == "degradation" for e in doc["events"])


def test_sigterm_dumps_flight_ring(tmp_path):
    script = textwrap.dedent(
        """
        import os, signal, sys
        from lightgbm_tpu.obs.flight import get_flight, install_sigterm_handler

        flight = get_flight()
        flight.configure(fault_dir=sys.argv[1], run_info={"drill": "sigterm"})
        for i in range(40):
            flight.note_event({"event": "iteration", "iter": i})
        assert install_sigterm_handler()
        os.kill(os.getpid(), signal.SIGTERM)
        raise SystemExit("survived SIGTERM")
        """
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", script, str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=240,
    )
    assert proc.returncode == -signal.SIGTERM, (
        proc.returncode, proc.stderr
    )
    dumps = list_flight_dumps(str(tmp_path))
    assert len(dumps) == 1
    with open(dumps[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "sigterm"
    assert doc["run_info"] == {"drill": "sigterm"}
    assert sum(1 for e in doc["events"] if e["event"] == "iteration") >= 32


def test_booster_health_api_and_exporter_during_training(tmp_path):
    X, y = _data()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    scraped = {}

    def scrape(env):
        if env.iteration == 2 and not scraped:
            url = f"http://127.0.0.1:{port}"
            scraped["metrics"] = urllib.request.urlopen(
                url + "/metrics", timeout=5
            ).read().decode()
            scraped["health"] = json.loads(
                urllib.request.urlopen(url + "/healthz", timeout=5).read()
            )

    booster = lgb.train(
        {
            "objective": "regression", "num_leaves": 7, "verbosity": -1,
            "telemetry": True, "obs_export_port": port,
        },
        lgb.Dataset(X, y), 5, callbacks=[scrape],
    )
    assert scraped, "scrape callback never ran"
    assert "lgbtpu_iterations_total" in scraped["metrics"]
    assert "lgbtpu_health_status 0" in scraped["metrics"]
    assert scraped["health"]["status"] == "ok"
    assert scraped["health"]["iter"] >= 2
    doc = booster.health()
    assert doc["schema"] == "lgbtpu.health.v1"
    assert doc["iter"] == 5
    assert doc["status"] == "ok"
    # the endpoint is torn down with the train loop
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2
        )
    # flight ring followed the run (last events are iterations 0..4)
    flight_iters = [
        e["iter"] for e in get_flight().events()
        if e.get("event") == "iteration"
    ]
    assert flight_iters == list(range(5))


def test_hist_gauges_present_when_telemetry_on():
    X, y = _data()
    lgb.train(
        {
            "objective": "regression", "num_leaves": 7, "verbosity": -1,
            "telemetry": True, "feature_fraction": 0.5,
            # the live-plane skip + int8 engage decisions are seg-histogram
            # features; the gauges are only published when that plane exists
            "hist_mode": "seg",
        },
        lgb.Dataset(X, y), 3,
    )
    gauges = get_session().gauges
    assert "hist/int8_engaged" in gauges
    assert "hist/live_plane_skip_ratio" in gauges
    assert 0.0 <= gauges["hist/live_plane_skip_ratio"] <= 1.0


# ------------------------------------------------------- retrace contract
def test_live_plane_zero_retrace_delta(tmp_path):
    X, y = _data()
    base = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    # warm every jit label with the plane disabled
    lgb.train(dict(base, health_watchdog=False), lgb.Dataset(X, y), 3)
    before = dict(lgb.compile_counts_by_label())
    # same shapes with the full live plane on: watchdog + flight + exporter
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    lgb.train(
        dict(
            base,
            telemetry=True,
            telemetry_out=str(tmp_path / "events.jsonl"),
            health_watchdog=True,
            obs_export_port=port,
            flight_capacity=64,
        ),
        lgb.Dataset(X, y), 3,
    )
    after = dict(lgb.compile_counts_by_label())
    assert after == before, (
        f"live ops plane caused retraces: before={before} after={after}"
    )
