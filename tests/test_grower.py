"""Grower correctness against NumPy oracles.

Mirrors the reference's unit-level checks of histogram/split math
(tests/cpp_tests) via property tests instead of GoogleTest.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import leaf_histogram_segment, leaf_histogram_onehot
from lightgbm_tpu.ops.split import best_split, leaf_gain
from lightgbm_tpu.ops.grower import GrowerParams, grow_tree


def _rand_problem(n=500, f=4, b=16, seed=0):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
    grad = rng.normal(size=n).astype(np.float32)
    hess = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    return bins, grad, hess


def _np_histogram(bins, grad, hess, mask, b):
    n, f = bins.shape
    out = np.zeros((f, b, 3), dtype=np.float64)
    for j in range(f):
        for i in range(n):
            out[j, bins[i, j], 0] += grad[i] * mask[i]
            out[j, bins[i, j], 1] += hess[i] * mask[i]
            out[j, bins[i, j], 2] += mask[i]
    return out


@pytest.mark.parametrize("impl", [leaf_histogram_segment, leaf_histogram_onehot])
def test_histogram_matches_numpy(impl):
    bins, grad, hess = _rand_problem()
    mask = (np.arange(len(grad)) % 3 == 0).astype(np.float32)
    got = np.asarray(impl(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
                          jnp.asarray(mask), 16))
    want = _np_histogram(bins, grad, hess, mask, 16)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def _np_best_split(hist, pg, ph, pc, num_bins, nan_bins, l1=0.0, l2=0.0,
                   min_data=1, min_hess=0.0, min_gain=0.0):
    """Brute-force best split over all (feature, bin, direction)."""
    def gain1(g, h):
        t = np.sign(g) * max(abs(g) - l1, 0.0)
        return t * t / (h + l2 + 1e-15)

    best = (-np.inf, -1, -1, False)
    parent_gain = gain1(pg, ph)
    f, b, _ = hist.shape
    for j in range(f):
        nb = nan_bins[j]
        nan_stats = hist[j, nb] if nb >= 0 else np.zeros(3)
        ordered = [i for i in range(num_bins[j]) if i != nb]
        for directions in ([False, True] if nb >= 0 else [False]):
            lg = lh = lc = 0.0
            if directions:
                lg, lh, lc = nan_stats
            for t_i, bin_i in enumerate(ordered[:-1]):
                lg += hist[j, bin_i, 0]
                lh += hist[j, bin_i, 1]
                lc += hist[j, bin_i, 2]
                rg, rh, rc = pg - lg, ph - lh, pc - lc
                if lc < min_data or rc < min_data or lh < min_hess or rh < min_hess:
                    continue
                g = gain1(lg, lh) + gain1(rg, rh) - parent_gain - min_gain
                if g > best[0]:
                    best = (g, j, bin_i, directions)
    return best


def test_best_split_matches_bruteforce():
    for seed in range(5):
        bins, grad, hess = _rand_problem(seed=seed, n=300, f=3, b=8)
        mask = np.ones(len(grad), dtype=np.float32)
        hist = _np_histogram(bins, grad, hess, mask, 8).astype(np.float32)
        pg, ph, pc = hist[0].sum(axis=0)
        num_bins = np.array([8, 8, 8], dtype=np.int32)
        nan_bins = np.array([-1, 7, -1], dtype=np.int32)  # feature 1 has a NaN bin
        fm = np.ones(3, dtype=bool)
        cand = jax.tree_util.tree_map(
            np.asarray,
            best_split(
                jnp.asarray(hist), pg, ph, pc,
                jnp.asarray(num_bins), jnp.asarray(nan_bins), jnp.asarray(fm),
                lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=1,
                min_sum_hessian_in_leaf=0.0, min_gain_to_split=0.0,
            ),
        )
        want_gain, want_f, want_b, want_dl = _np_best_split(
            hist.astype(np.float64), pg, ph, pc, num_bins, nan_bins
        )
        assert np.isclose(cand.gain, want_gain, rtol=1e-3, atol=1e-3), (seed,)
        # the argmax itself can tie across features; check the gain primarily
        got_gain_refit = _np_best_split(
            hist.astype(np.float64), pg, ph, pc, num_bins, nan_bins
        )[0]
        assert np.isclose(cand.gain, got_gain_refit, rtol=1e-3, atol=1e-3)


def test_min_data_constraint_respected():
    bins, grad, hess = _rand_problem(n=100, f=2, b=8, seed=7)
    mask = np.ones(100, dtype=np.float32)
    hist = jnp.asarray(_np_histogram(bins, grad, hess, mask, 8).astype(np.float32))
    pg, ph, pc = np.asarray(hist[0].sum(axis=0))
    cand = best_split(
        hist, pg, ph, pc,
        jnp.asarray([8, 8], dtype=jnp.int32), jnp.asarray([-1, -1], dtype=jnp.int32),
        jnp.ones(2, dtype=bool),
        lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=60,
        min_sum_hessian_in_leaf=0.0, min_gain_to_split=0.0,
    )
    # no split can satisfy 60+60 > 100 rows
    assert not np.isfinite(np.asarray(cand.gain))


class NumpyTreeOracle:
    """Greedy leaf-wise tree in NumPy — small-scale ground truth."""

    def __init__(self, bins, grad, hess, num_bins, nan_bins, num_leaves,
                 min_data=1, l2=0.0):
        self.bins, self.grad, self.hess = bins, grad, hess
        self.num_bins, self.nan_bins = num_bins, nan_bins
        self.num_leaves, self.min_data, self.l2 = num_leaves, min_data, l2
        self.b = int(num_bins.max())

    def fit(self):
        n, f = self.bins.shape
        leaf_id = np.zeros(n, dtype=np.int32)
        leaves = {0: np.ones(n, dtype=bool)}
        splits = []
        while len(leaves) < self.num_leaves:
            best = (-np.inf, None)
            for lid, rows in leaves.items():
                hist = _np_histogram(self.bins[rows], self.grad[rows],
                                     self.hess[rows], np.ones(rows.sum()), self.b)
                pg = self.grad[rows].sum()
                ph = self.hess[rows].sum()
                pc = float(rows.sum())
                g, j, t, dl = _np_best_split(
                    hist, pg, ph, pc, self.num_bins, self.nan_bins,
                    l2=self.l2, min_data=self.min_data)
                if g > best[0]:
                    best = (g, (lid, j, t, dl))
            if best[1] is None or best[0] <= 0:
                break
            lid, j, t, dl = best[1]
            rows = leaves[lid]
            col = self.bins[:, j]
            nb = self.nan_bins[j]
            go_left = (col <= t) | (dl & (col == nb) & (nb >= 0))
            new_id = len(leaves)
            left = rows & go_left
            right = rows & ~go_left
            leaves[lid] = left
            leaves[new_id] = right
            leaf_id[right] = new_id
            splits.append((lid, j, t, best[0]))
        values = {}
        for lid, rows in leaves.items():
            g, h = self.grad[rows].sum(), self.hess[rows].sum()
            values[lid] = -g / (h + self.l2 + 1e-15)
        return leaf_id, values, splits


@pytest.mark.parametrize("num_leaves,seed", [(4, 0), (8, 1), (16, 2)])
def test_grow_tree_matches_numpy_oracle(num_leaves, seed):
    bins, grad, hess = _rand_problem(n=400, f=3, b=8, seed=seed)
    num_bins = np.array([8, 8, 8], dtype=np.int32)
    nan_bins = np.array([-1, -1, -1], dtype=np.int32)
    params = GrowerParams(
        num_leaves=num_leaves, max_bin=8, min_data_in_leaf=5,
        min_sum_hessian_in_leaf=0.0, lambda_l2=0.1, hist_method="segment",
    )
    tree, leaf_id = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(len(grad), dtype=jnp.float32),
        jnp.asarray(num_bins), jnp.asarray(nan_bins),
        jnp.ones(3, dtype=bool), params,
    )
    oracle = NumpyTreeOracle(bins, grad.astype(np.float64), hess.astype(np.float64),
                             num_bins, nan_bins, num_leaves, min_data=5, l2=0.1)
    o_leaf_id, o_values, o_splits = oracle.fit()

    got_leaves = int(tree.num_leaves)
    assert got_leaves == len(o_values)
    # same partition of rows into leaves
    np.testing.assert_array_equal(np.asarray(leaf_id), o_leaf_id)
    # same leaf values
    got_values = np.asarray(tree.leaf_value)
    for lid, v in o_values.items():
        assert np.isclose(got_values[lid], v, rtol=1e-3, atol=1e-4), lid
    # same split sequence (leaf, feature, bin)
    got_feat = np.asarray(tree.split_feature)
    got_bin = np.asarray(tree.split_bin)
    for i, (lid, j, t, g) in enumerate(o_splits):
        assert got_feat[i] == j
        assert got_bin[i] == t


def test_grow_tree_respects_max_depth():
    bins, grad, hess = _rand_problem(n=1000, f=4, b=16, seed=3)
    params = GrowerParams(
        num_leaves=31, max_bin=16, max_depth=2, min_data_in_leaf=1,
        min_sum_hessian_in_leaf=0.0, hist_method="segment",
    )
    tree, _ = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(len(grad), dtype=jnp.float32),
        jnp.full(4, 16, dtype=jnp.int32), jnp.full(4, -1, dtype=jnp.int32),
        jnp.ones(4, dtype=bool), params,
    )
    assert int(tree.num_leaves) <= 4  # depth 2 -> at most 4 leaves
    depths = np.asarray(tree.leaf_depth)[: int(tree.num_leaves)]
    assert depths.max() <= 2


def test_grow_tree_tree_structure_consistent():
    bins, grad, hess = _rand_problem(n=500, f=4, b=16, seed=4)
    params = GrowerParams(num_leaves=12, max_bin=16, min_data_in_leaf=5,
                          hist_method="segment")
    tree, leaf_id = grow_tree(
        jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(len(grad), dtype=jnp.float32),
        jnp.full(4, 16, dtype=jnp.int32), jnp.full(4, -1, dtype=jnp.int32),
        jnp.ones(4, dtype=bool), params,
    )
    nl = int(tree.num_leaves)
    lc = np.asarray(tree.left_child)[: nl - 1]
    rc = np.asarray(tree.right_child)[: nl - 1]
    # every leaf referenced exactly once; every internal node (except root)
    # referenced exactly once
    leaf_refs = sorted([-c - 1 for c in np.concatenate([lc, rc]) if c < 0])
    node_refs = sorted([c for c in np.concatenate([lc, rc]) if c >= 0])
    assert leaf_refs == list(range(nl))
    assert node_refs == list(range(1, nl - 1))
    # walking rows through the tree reproduces leaf_id
    bins_np = np.asarray(bins)
    sf = np.asarray(tree.split_feature)
    sb = np.asarray(tree.split_bin)
    dl = np.asarray(tree.default_left)
    for i in range(0, 500, 37):
        node = 0
        while True:
            j, t = sf[node], sb[node]
            go_left = bins_np[i, j] <= t
            nxt = lc[node] if go_left else rc[node]
            if nxt < 0:
                assert -nxt - 1 == np.asarray(leaf_id)[i]
                break
            node = nxt
