"""Custom text-parser plugin (parser_config_file / register_parser) —
reference: Parser::CreateParser's customized add-on + ParserFactory
(include/LightGBM/dataset.h:445-455, src/io/parser.cpp:288) and
GenerateParserConfigStr's header/label_idx appending."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.parser import (  # noqa: E402
    create_parser,
    generate_parser_config_str,
    get_from_parser_config,
)


def _pipe_factory(config_str):
    # label|f0|f1|... with a config-chosen delimiter
    delim = get_from_parser_config(config_str, "delimiter") or "|"

    def parse_line(line):
        toks = line.split(delim)
        return [float(t) for t in toks[1:]], float(toks[0])

    return parse_line


def _sparse_factory(config_str):
    def parse_line(line):
        toks = line.split()
        feats = [
            (int(t.split(":")[0]), float(t.split(":")[1])) for t in toks[1:]
        ]
        return feats, float(toks[0])

    return parse_line


def test_custom_dense_parser_end_to_end(tmp_path):
    lgb.register_parser("PipeParser", _pipe_factory)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 3))
    y = X[:, 0] + rng.normal(scale=0.1, size=400)
    data = tmp_path / "d.pipe"
    data.write_text(
        "\n".join(
            ";".join([f"{y[i]:.6f}"] + [f"{v:.6f}" for v in X[i]])
            for i in range(400)
        )
    )
    conf = tmp_path / "parser.conf"
    conf.write_text("className=PipeParser\ndelimiter=;\n")
    p = {"objective": "regression", "verbosity": -1,
         "parser_config_file": str(conf)}
    ds = lgb.Dataset(str(data), params=p)
    ds.construct()
    assert ds.num_data == 400 and ds.num_total_features == 3
    np.testing.assert_allclose(ds.get_label(), y, atol=1e-5)
    b = lgb.train(p, ds, 5)
    assert np.isfinite(b.predict(X)).all()
    # the generated config str persists with the binary dataset
    assert "className=PipeParser" in ds.parser_config_str
    f = str(tmp_path / "d.bin")
    ds.save_binary(f)
    d2 = lgb.Dataset(f)
    d2.construct()
    assert "className=PipeParser" in d2.parser_config_str


def test_custom_sparse_parser(tmp_path):
    pytest.importorskip("scipy.sparse")
    lgb.register_parser("SparseColon", _sparse_factory)
    data = tmp_path / "d.sp"
    # label idx:val pairs — but routed through the CUSTOM parser, so the
    # LibSVM auto-detection must NOT be what parses it
    lines = ["1 0:1.5 3:2.0", "0 1:1.0", "1 0:0.5 2:4.0", "0 3:1.0"] * 50
    data.write_text("\n".join(lines))
    conf = tmp_path / "parser.conf"
    conf.write_text("className=SparseColon\n")
    p = {"objective": "binary", "verbosity": -1, "min_data_in_leaf": 5,
         "min_data_in_bin": 1, "parser_config_file": str(conf)}
    ds = lgb.Dataset(str(data), params=p)
    ds.construct()
    assert ds.num_data == 200 and ds.num_total_features == 4
    b = lgb.train(p, ds, 3)
    assert b.num_trees() >= 1


def test_unregistered_classname_actionable_error(tmp_path):
    conf = tmp_path / "parser.conf"
    conf.write_text("className=NoSuchParser\n")
    data = tmp_path / "d.csv"
    data.write_text("1,2\n0,3\n")
    with pytest.raises(ValueError, match="register_parser"):
        lgb.Dataset(
            str(data), params={"parser_config_file": str(conf)}
        ).construct()


def test_config_without_classname_falls_back(tmp_path):
    conf = tmp_path / "parser.conf"
    conf.write_text("somekey=1\n")
    data = tmp_path / "d.csv"
    rows = "\n".join(f"{i % 2},{i},{2 * i}" for i in range(50))
    data.write_text(rows)
    ds = lgb.Dataset(str(data), params={"parser_config_file": str(conf)})
    ds.construct()  # CSV auto-detection handles it
    assert ds.num_data == 50


def test_generate_parser_config_str_appends_context(tmp_path):
    conf = tmp_path / "parser.conf"
    conf.write_text("className=X")
    s = generate_parser_config_str(str(conf), header=True, label_idx=2)
    assert get_from_parser_config(s, "className") == "X"
    assert get_from_parser_config(s, "header") == "true"
    assert get_from_parser_config(s, "label_idx") == "2"
    assert create_parser("") is None


def test_sparse_parser_label_only_first_row_and_sidecar(tmp_path):
    """A label-only first row must not lock the loader into dense mode,
    and sidecar .query files load on the custom-parser path too."""
    pytest.importorskip("scipy.sparse")
    lgb.register_parser("SparseColon2", _sparse_factory)
    data = tmp_path / "d.sp"
    lines = ["0"] + ["1 0:1.5 3:2.0", "0 1:1.0", "1 2:4.0"] * 40
    data.write_text("\n".join(lines))
    (tmp_path / "d.sp.query").write_text("\n".join(["11"] * 11))
    conf = tmp_path / "parser.conf"
    conf.write_text("className=SparseColon2\n")
    p = {"objective": "lambdarank", "verbosity": -1, "min_data_in_leaf": 5,
         "min_data_in_bin": 1, "parser_config_file": str(conf)}
    ds = lgb.Dataset(str(data), params=p)
    ds.construct()
    assert ds.num_data == 121 and ds.num_total_features == 4
    assert ds.get_group() is not None and sum(ds.get_group()) == 121
    b = lgb.train(p, ds, 2)
    assert b.num_trees() >= 1


def test_label_column_by_name(tmp_path):
    data = tmp_path / "d.csv"
    rows = ["a,target,b"] + [f"{i},{i % 2},{2 * i}" for i in range(60)]
    data.write_text("\n".join(rows))
    p = {"objective": "binary", "verbosity": -1, "header": True,
         "label_column": "name:target"}
    ds = lgb.Dataset(str(data), params=p)
    ds.construct()
    assert ds.num_data == 60
    np.testing.assert_array_equal(
        ds.get_label(), np.arange(60) % 2
    )
