"""bagging_by_query: whole queries sampled as units (reference:
src/boosting/bagging.hpp:52 — per-query BaggingHelper + index rebuild;
here one Bernoulli per query expanded to rows by a static jnp.repeat)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.boosting.sampling import BaggingStrategy, create_sample_strategy


def _mask(strategy, it, n, seed=0):
    g = jnp.zeros((1, n), jnp.float32)
    m, _, _ = strategy.sample(it, g, g, jax.random.PRNGKey(seed))
    return np.asarray(m)


def test_mask_constant_within_queries():
    sizes = np.array([7, 3, 12, 1, 9, 20, 8], np.int64)
    n = int(sizes.sum())
    cfg = Config.from_params({"bagging_fraction": 0.5, "bagging_freq": 1})
    s = BaggingStrategy(cfg, n, query_sizes=sizes)
    for it in range(4):
        m = _mask(s, it, n, seed=it)
        o = 0
        for sz in sizes:
            q = m[o : o + sz]
            assert (q == q[0]).all(), "query partially sampled"
            o += sz
        assert set(np.unique(m)) <= {0.0, 1.0}


def test_padding_rows_never_in_bag():
    sizes = np.array([10, 10], np.int64)
    n = 32  # 12 padding rows
    cfg = Config.from_params({"bagging_fraction": 1.0, "bagging_freq": 1})
    s = BaggingStrategy(cfg, n, query_sizes=sizes)
    m = _mask(s, 0, n)
    assert (m[:20] == 1.0).all()
    assert (m[20:] == 0.0).all()


def test_fraction_approximately_respected():
    rng = np.random.default_rng(0)
    sizes = rng.integers(5, 15, size=400).astype(np.int64)
    n = int(sizes.sum())
    cfg = Config.from_params({"bagging_fraction": 0.3, "bagging_freq": 1})
    s = BaggingStrategy(cfg, n, query_sizes=sizes)
    kept = []
    for it in range(5):
        m = _mask(s, it, n, seed=it)
        o = 0
        k = 0
        for sz in sizes:
            k += int(m[o])
            o += sz
        kept.append(k / len(sizes))
    assert 0.2 < np.mean(kept) < 0.4


def test_refresh_respects_bagging_freq():
    sizes = np.array([16] * 10, np.int64)
    n = 160
    cfg = Config.from_params({"bagging_fraction": 0.5, "bagging_freq": 3})
    s = BaggingStrategy(cfg, n, query_sizes=sizes)
    m0 = _mask(s, 0, n, seed=1)
    m1 = _mask(s, 1, n, seed=2)  # no refresh: same mask despite new rng
    assert np.array_equal(m0, m1)
    m3 = _mask(s, 3, n, seed=3)
    assert not np.array_equal(m0, m3)  # refresh at freq boundary


def test_factory_requires_group():
    cfg = Config.from_params(
        {"bagging_by_query": True, "bagging_fraction": 0.5, "bagging_freq": 1}
    )
    with pytest.raises(ValueError, match="query information"):
        create_sample_strategy(cfg, 100)


def test_lambdarank_bagging_by_query_e2e():
    rng = np.random.default_rng(3)
    n, f = 1200, 6
    X = rng.normal(size=(n, f))
    y = rng.integers(0, 4, n).astype(float)
    grp = np.full(60, 20)
    params = {
        "objective": "lambdarank",
        "bagging_by_query": True,
        "bagging_fraction": 0.5,
        "bagging_freq": 1,
        "verbosity": -1,
        "metric": "ndcg",
        "eval_at": [3],
    }
    res = {}
    b = lgb.train(
        params,
        lgb.Dataset(X, y, group=grp),
        num_boost_round=15,
        valid_sets=[lgb.Dataset(X, y, group=grp)],
        valid_names=["t"],
        callbacks=[lgb.record_evaluation(res)],
    )
    assert b.num_trees() == 15
    assert res["t"]["ndcg@3"][-1] > 0.5  # learns something


def test_conflicting_strategies_rejected():
    base = {"bagging_by_query": True, "bagging_fraction": 0.5, "bagging_freq": 1}
    with pytest.raises(ValueError, match="GOSS"):
        create_sample_strategy(
            Config.from_params({**base, "boosting": "goss"}), 100,
            query_sizes=np.array([50, 50]),
        )
    with pytest.raises(ValueError, match="balanced"):
        create_sample_strategy(
            Config.from_params(
                {**base, "objective": "binary", "pos_bagging_fraction": 0.5}
            ),
            100,
            query_sizes=np.array([50, 50]),
        )


def test_inactive_bagging_is_noop():
    # bagging_by_query with bagging off (freq=0 default) must not require
    # group info — the reference only consults it inside active bagging
    cfg = Config.from_params({"bagging_by_query": True})
    s = create_sample_strategy(cfg, 100)
    m = _mask(s, 0, 100)
    assert (m == 1.0).all()
