"""Unified telemetry: per-iteration event stream, compile accounting,
collective byte model, JSONL sink (obs/ subsystem).

Reference analog: the C++ tree's only observability is ``global_timer``
(utils/common.h:979); the obs/ registry is its structured superset.
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu.obs.registry import get_session  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_session():
    ses = get_session()
    ses.configure(enabled=False)
    ses.reset()
    yield
    ses.configure(enabled=False)
    ses.reset()


def _data(n=400, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=n)
    return X, y


# --------------------------------------------------------------- event schema
def test_iteration_event_schema_and_jsonl(tmp_path):
    X, y = _data()
    sink = str(tmp_path / "events.jsonl")
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "metric": "l2",
        "telemetry": True,
        "telemetry_out": sink,
    }
    booster = lgb.train(
        params,
        lgb.Dataset(X, y),
        5,
        valid_sets=[lgb.Dataset(X, y)],
        valid_names=["t"],
    )
    tel = booster.telemetry()
    events = [e for e in tel["events"] if e["event"] == "iteration"]
    assert len(events) == 5
    for it, e in enumerate(events):
        assert e["iter"] == it
        assert e["wall_ms"] > 0
        assert isinstance(e["phases"], dict) and e["phases"]
        assert all(v >= 0 for v in e["phases"].values())
        assert e["compiles_delta"] >= 0
        assert e["leaf_batch"] == 1
    # phases cover the booster hot path
    all_phases = set().union(*(e["phases"] for e in events))
    assert {"gradients", "sample", "grow"} <= all_phases
    assert tel["counters"]["iterations"] == 5
    assert tel["compile_count"] > 0
    # one JSONL line per iteration, eval metrics annotated into the line;
    # train() appends the end-of-train host_rollup + train_summary records
    lines = [json.loads(l) for l in open(sink)]
    kinds = [l["event"] for l in lines]
    assert kinds[:5] == ["iteration"] * 5
    assert kinds[5:] == ["host_rollup", "train_summary"]
    assert any("eval" in l and "t/l2" in l["eval"] for l in lines)
    summary = lines[-1]
    assert summary["counters"]["iterations"] == 5
    assert isinstance(summary["gauges"], dict)


def test_telemetry_callback_collects_history():
    X, y = _data()
    cb = lgb.TelemetryCallback()
    lgb.train(
        {
            "objective": "regression",
            "num_leaves": 7,
            "verbosity": -1,
            "metric": "l2",
            "telemetry": True,
        },
        lgb.Dataset(X, y),
        4,
        valid_sets=[lgb.Dataset(X, y)],
        valid_names=["t"],
        callbacks=[cb],
    )
    assert len(cb.history) == 4
    assert cb.history[0]["event"] == "iteration"
    assert "t/l2" in cb.history[0]["eval"]


# ------------------------------------------------------------ disabled = noop
def test_disabled_records_nothing_and_phase_is_shared_noop():
    ses = get_session()
    X, y = _data()
    lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, y),
        3,
    )
    assert ses.events == []
    assert ses.counters == {}
    assert ses.gauges == {}
    # structural overhead guard: disabled phase() hands back one shared
    # no-op object (no allocation, no timing) — the <2% bench budget
    p1 = ses.phase("grow")
    p2 = ses.phase("gradients")
    assert p1 is p2
    ses.record({"event": "x"})
    assert ses.events == []
    ses.inc("n")
    ses.set_gauge("g", 1.0)
    assert ses.counters == {} and ses.gauges == {}


# --------------------------------------------------------- compile accounting
def test_no_recompile_after_warmup_train():
    X, y = _data(n=500)
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "telemetry": True,
    }
    booster = lgb.train(params, lgb.Dataset(X, y), 8)
    events = [
        e for e in booster.telemetry()["events"] if e["event"] == "iteration"
    ]
    assert len(events) == 8
    # the first iterations trace; after warmup every jit call must hit cache
    assert sum(e["compiles_delta"] for e in events[:3]) > 0
    assert all(e["compiles_delta"] == 0 for e in events[3:])


def test_no_recompile_streaming_predict_varied_batches():
    X, y = _data(n=600)
    booster = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, y),
        3,
    )
    chunk = 128
    booster.params["pred_chunk_rows"] = chunk
    booster.config = type(booster.config).from_params(booster.params)
    # warmup covers the bucket ladder once
    booster.predict(X[:chunk])
    booster.predict(X)
    from lightgbm_tpu.predict import streaming_compile_count

    before_stream = streaming_compile_count()
    before_global = lgb.compile_count()
    for n in (1, 7, 63, 128, 200, 311, 600):
        booster.predict(X[:n])
    assert streaming_compile_count() == before_stream
    assert lgb.compile_count() == before_global


def test_instrumented_jit_counts_retraces_by_label():
    from lightgbm_tpu.obs.jit import instrumented_jit

    import jax.numpy as jnp

    before = dict(lgb.compile_counts_by_label())

    @instrumented_jit(label="test/add1")
    def add1(x):
        return x + 1

    add1(jnp.ones((4,)))
    add1(jnp.ones((4,)))  # cache hit: no retrace
    add1(jnp.ones((8,)))  # new shape: retrace
    by_label = lgb.compile_counts_by_label()
    assert by_label["test/add1"] - before.get("test/add1", 0) == 2


def test_compile_counter_is_exact_under_threads():
    """compile_count()/compile_counts_by_label() take the same lock as
    note_compile's read-modify-write, so concurrent noters never lose an
    increment and readers never observe a torn count/label pair."""
    import threading

    from lightgbm_tpu.obs.jit import (
        compile_count,
        compile_counts_by_label,
        note_compile,
    )

    n_threads, per_thread = 8, 250
    before_total = compile_count()
    before_label = compile_counts_by_label().get("test/threads", 0)
    barrier = threading.Barrier(n_threads)

    def noter():
        barrier.wait()
        for _ in range(per_thread):
            note_compile("test/threads")
            assert compile_count() >= 0  # interleave reads with writes

    threads = [threading.Thread(target=noter) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert compile_count() - before_total == n_threads * per_thread
    assert (
        compile_counts_by_label()["test/threads"] - before_label
        == n_threads * per_thread
    )


def test_predict_events_when_enabled():
    X, y = _data(n=500)
    booster = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, y),
        3,
    )
    ses = get_session().configure(enabled=True)
    ses.reset()
    booster.params["pred_chunk_rows"] = 128
    booster.config = type(booster.config).from_params(booster.params)
    booster.predict(X)
    chunk_evs = [e for e in ses.events if e["event"] == "predict_chunk"]
    summaries = [e for e in ses.events if e["event"] == "predict"]
    assert len(summaries) == 1
    assert summaries[0]["chunks"] == len(chunk_evs) >= 2
    assert summaries[0]["rows"] == 500
    assert set(summaries[0]["phases"]) == {
        "bin_ms", "transfer_ms", "walk_ms", "host_ms"
    }


# ------------------------------------------------------ collective byte model
def test_psum_bytes_model():
    from lightgbm_tpu.parallel import psum_bytes_per_iteration

    f, b = 28, 256
    hist = f * b * 3 * 4
    serial = psum_bytes_per_iteration(10, f, b, leaf_batch=1, mesh_size=4)
    assert serial["steps"] == 10
    assert serial["hist_bytes"] == 11 * hist  # 10 splits + root
    assert serial["count_bytes"] == 10 * 2 * 4 + 8
    batched = psum_bytes_per_iteration(10, f, b, leaf_batch=4, mesh_size=4)
    assert batched["steps"] == 3  # ceil(10 / 4)
    assert batched["hist_bytes"] == (3 * 4 + 1) * hist
    ring = 2 * (4 - 1) / 4
    assert batched["ring_bytes_per_device"] == pytest.approx(
        (batched["hist_bytes"] + batched["count_bytes"]) * ring
    )
    none = psum_bytes_per_iteration(0, f, b)
    assert none["steps"] == 0 and none["hist_bytes"] == hist


def test_collective_gauges_under_data_parallel():
    X, y = _data(n=512)
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "tree_learner": "data",
        "telemetry": True,
    }
    booster = lgb.train(params, lgb.Dataset(X, y), 3)
    tel = booster.telemetry()
    if booster._mesh is None:
        pytest.skip("single device: data-parallel mesh not formed")
    events = [e for e in tel["events"] if e["event"] == "iteration"]
    assert all("collective" in e for e in events)
    coll = events[-1]["collective"]
    assert coll["hist_bytes"] > 0 and coll["steps"] > 0
    assert tel["gauges"]["collective_hist_bytes"] == coll["hist_bytes"]
    assert tel["gauges"]["collective_ring_bytes_per_device"] >= 0


# --------------------------------------------- executable accounting (cost/*)
def test_cost_memory_gauges_train_and_predict(tmp_path):
    """obs_device_accounting captures executable cost/memory analysis for
    BOTH the training grower and the streaming predictor, and the families
    round-trip through the JSONL sink's train_summary record."""
    X, y = _data(n=500)
    sink = str(tmp_path / "events.jsonl")
    params = {
        "objective": "regression",
        "num_leaves": 7,
        "verbosity": -1,
        "telemetry": True,
        "telemetry_out": sink,
        "obs_device_accounting": True,
    }
    booster = lgb.train(params, lgb.Dataset(X, y), 3)
    booster.predict(X)
    gauges = booster.telemetry()["gauges"]
    # train: the grower's jit label carries FLOPs and the full memory family
    assert gauges["cost/grow_tree/flops"] > 0
    assert gauges["cost/grow_tree/bytes_accessed"] > 0
    assert gauges["memory/grow_tree/temp_bytes"] > 0
    assert gauges["memory/grow_tree/argument_bytes"] > 0
    assert gauges["memory/grow_tree/output_bytes"] > 0
    # streaming predict: per-variant label (packed/stacked/real)
    pred_cost = [
        k for k in gauges if k.startswith("cost/predict/stream/")
    ]
    assert pred_cost, f"no predict cost gauges in {sorted(gauges)}"
    assert all(gauges[k] >= 0 for k in pred_cost)
    # JSONL round-trip: the train_summary line carries the gauge families
    lines = [json.loads(l) for l in open(sink)]
    summary = [l for l in lines if l["event"] == "train_summary"][-1]
    assert summary["gauges"]["cost/grow_tree/flops"] == pytest.approx(
        gauges["cost/grow_tree/flops"]
    )
    assert "memory/grow_tree/temp_bytes" in summary["gauges"]


def test_device_accounting_off_means_no_cost_gauges():
    X, y = _data()
    booster = lgb.train(
        {
            "objective": "regression",
            "num_leaves": 7,
            "verbosity": -1,
            "telemetry": True,
        },
        lgb.Dataset(X, y),
        2,
    )
    gauges = booster.telemetry()["gauges"]
    assert not [k for k in gauges if k.startswith(("cost/", "memory/"))]


def test_device_memory_graceful_noop_on_unsupported_backend():
    """CPU devices report no memory_stats: sampling must silently no-op
    (latching the unsupported probe) instead of erroring or emitting
    garbage gauges."""
    from lightgbm_tpu.obs import device as obs_device

    ses = get_session().configure(enabled=True, device_accounting=True)
    obs_device.sample_device_memory("test")
    supported = obs_device.device_memory_supported()
    has_stats = any(
        d.memory_stats() for d in jax.local_devices()
    )
    assert supported is has_stats or (supported is None)
    if not has_stats:
        assert not [
            k for k in ses.gauges if k.startswith("memory/hbm_")
        ]


# -------------------------------------------------------------- profiler glue
def test_profile_trace_dir_writes_trace(tmp_path):
    import os

    trace_dir = str(tmp_path / "trace")
    X, y = _data()
    lgb.train(
        {
            "objective": "regression",
            "num_leaves": 7,
            "verbosity": -1,
            "profile_trace_dir": trace_dir,
            "profile_iter_start": 1,
            "profile_iter_end": 2,
        },
        lgb.Dataset(X, y),
        4,
    )
    # start/stop ran and produced profiler output (plugin layout varies)
    assert os.path.isdir(trace_dir)
    found = [
        os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs
    ]
    assert found, "profiler trace produced no files"


def test_sync_timing_phases_cover_wall():
    X, y = _data(n=500)
    params = {
        "objective": "regression",
        "num_leaves": 15,
        "verbosity": -1,
        "telemetry": True,
        "obs_sync_timing": True,
    }
    booster = lgb.train(params, lgb.Dataset(X, y), 4)
    events = [
        e for e in booster.telemetry()["events"] if e["event"] == "iteration"
    ]
    # with per-phase blocking the measured phases account for most of the
    # iteration wall (bookkeeping outside phases stays small)
    steady = events[-1]
    assert sum(steady["phases"].values()) <= steady["wall_ms"] + 1.0
    assert steady["phases"]["grow"] > 0
