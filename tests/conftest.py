"""Test config: make an 8-device virtual CPU mesh available.

Multi-chip hardware isn't available in CI; sharding tests run over
``--xla_force_host_platform_device_count=8`` as the reference's distributed
tests run N CLI processes on localhost (tests/distributed/_test_distributed.py).

jax may already be imported (sitecustomize preloads the TPU tunnel), so the
flag is injected before the FIRST CPU client creation — the CPU backend is
lazy, which keeps this effective; tests that need the mesh use
``jax.devices("cpu")`` explicitly.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Run the whole suite on the virtual CPU mesh: correctness tests don't need
# the (remote-tunneled, slow-compile) TPU, and serial-vs-sharded comparisons
# must run on ONE platform so reduction-order diffs don't flip tied splits.
# LGBM_TPU_NATIVE=1 keeps the TPU visible instead, expanding the suite with
# the `native_tpu` tier:  LGBM_TPU_NATIVE=1 pytest -m native_tpu
_NATIVE_RUN = os.environ.get("LGBM_TPU_NATIVE") == "1"
if not _NATIVE_RUN:
    os.environ["JAX_PLATFORMS"] = "cpu"

# The env var alone is NOT enough: a TPU-tunnel shim (sitecustomize) may have
# already set the jax_platforms CONFIG to prefer its backend, which overrides
# the env and routes every default-placed op through the tunnel (and hangs the
# whole suite if the tunnel is down). Force the config before any backend
# initializes — jax may be imported, but its backends are still lazy here.
import jax  # noqa: E402

if not _NATIVE_RUN:
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass

# Persistent compilation cache: the full suite compiles ~1000+ XLA programs
# in one process, which can segfault XLA:CPU's LLVM JIT near the end of the
# run (observed deterministically at the same suite position; crash stack is
# inside backend_compile_and_load).  Caching compiled artifacts on disk cuts
# fresh LLVM work massively on repeat runs; tools/run_tests.sh additionally
# chunks the suite across processes.  LGBM_TPU_NO_JAX_CACHE=1 opts out.
#
# Only programs that took >=1s to compile are cached: under the virtual
# 8-device platform, tiny entries written by one process occasionally
# deserialize into corrupted executables in a second process (observed as
# NaN scores from a donated scatter-add that is byte-correct when compiled
# fresh).  Big entries carry the warm-start value and read back cleanly.
if not os.environ.get("LGBM_TPU_NO_JAX_CACHE"):
    try:
        jax.config.update("jax_compilation_cache_dir", "/tmp/lgbm_jax_cache")
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: compile-heavy / multi-process tests — the default tier is "
        "`-m 'not slow'` (<5 min); run the full suite without the filter",
    )
    config.addinivalue_line(
        "markers",
        "native_tpu: needs a real TPU; run with "
        "`LGBM_TPU_NATIVE=1 pytest -m native_tpu` when hardware is attached",
    )


# Measured-slow tests (round-3 full-suite --durations on the CI CPU): the
# compile-heavy end-to-end combinations.  Every kernel ORACLE (seg sort /
# partition / histogram / forest-walk vs reference semantics), the golden
# parity tests, one consistency example and the serial-vs-sharded equality
# oracle stay in the default tier.  Centralized here so the tier is one
# list, not 40 scattered decorators.
_SLOW_TESTS = {
    "test_consistency.py::test_training_parity_on_example[lambdarank]",
    "test_consistency.py::test_training_parity_on_example[multiclass_classification]",
    "test_consistency.py::test_training_parity_on_example[binary_classification]",
    "test_launcher.py::test_two_process_pre_partition_training",
    "test_launcher.py::test_two_process_psum",
    "test_launcher.py::test_two_process_binning_sync",
    "test_launcher.py::test_two_process_bagging_by_query",
    "test_parallel.py::test_booster_data_parallel_multiclass_valid",
    "test_parallel.py::test_booster_data_parallel_padded_rows",
    "test_parallel.py::test_booster_data_parallel_xentlambda_padded",
    "test_parallel.py::test_booster_data_parallel_bagging_runs",
    "test_booster.py::test_categorical_feature",
    "test_booster.py::test_early_stopping_and_best_iteration_predict",
    "test_booster.py::test_rf",
    "test_booster.py::test_sklearn_classifier",
    "test_monotone.py::test_intermediate_not_worse_than_basic",
    "test_monotone.py::test_advanced_not_worse_than_intermediate",
    "test_monotone.py::test_advanced_monotone_with_path_smooth",
    "test_monotone.py::test_advanced_monotone_with_categoricals",
    "test_dask.py::test_dask_regressor_two_workers_matches_single_process",
    "test_dask.py::test_dask_ranker_groups_not_split",
    "test_dask.py::test_dask_classifier_multiclass",
    "test_monotone.py::test_monotone_property[advanced]",
    "test_codegen.py::test_cpp_codegen_multiclass_softmax",
    "test_codegen.py::test_cpp_codegen_xentlambda_softplus",
    "test_feature_parallel.py::test_feature_parallel_seg_categorical_matches_serial",
    "test_categorical.py::test_e2e_categorical_nan_goes_right",
    "test_categorical.py::test_e2e_categorical_roundtrip_and_consistency",
    "test_categorical.py::test_e2e_categorical_beats_frequency_rank",
    "test_categorical.py::test_mixed_numeric_and_categorical",
    "test_cegb.py::test_coupled_penalty_steers_feature_choice",
    "test_cegb.py::test_coupled_penalty_paid_once_unlocks_feature",
    "test_cegb.py::test_split_penalty_prunes_growth",
    "test_cegb.py::test_huge_coupled_penalty_blocks_feature_entirely",
    "test_api_surface.py::test_booster_utilities",
    "test_api_surface.py::test_sequence_ingestion",
    "test_position_debias.py::test_position_bias_factors_update_and_change_gradients",
    "test_position_debias.py::test_position_none_unchanged",
    "test_histogram_int8.py::test_int8_training_path_matches_segment",
    "test_cv_ranking.py::test_ranking_cv_end_to_end",
    "test_quantized.py::test_quantized_training_close_to_exact[False]",
    "test_quantized.py::test_quantized_training_close_to_exact[True]",
    "test_extra_trees.py::test_extra_trees_randomizes_thresholds_but_learns",
    "test_forced_splits.py::test_root_split_is_forced",
    "test_predict.py::test_loaded_categorical_model_device_walker",
    "test_predict.py::test_pred_early_stop_matches_sequential_reference",
    "test_predict.py::test_pred_early_stop_multiclass_margin",
    "test_observability.py::test_register_logger_redirects_eval_lines",
    "test_voting.py::test_voting_quality_near_data_parallel",
    "test_voting.py::test_voting_trains_and_learns_high_f",
    "test_forest_walk.py::test_forest_walk_many_classes",
    "test_param_combos.py::test_combo_trains_and_roundtrips",
    "test_param_combos.py::test_objective_combos",
    # second-round trims (tier measured 7:30 -> target <5:00); each family
    # keeps a representative in the default tier
    "test_parallel.py::test_booster_data_parallel_matches_serial",
    "test_monotone.py::test_monotone_property[basic]",
    "test_forest_walk.py::test_forest_walk_wide_tree_four_half_lookup",
    "test_forest_walk.py::test_device_binned_walk_matches_slow_path",
    "test_voting.py::test_voting_aliases_to_data_below_cutover",
    "test_device_metrics.py::test_multi_logloss_device_matches_host",
    "test_inspection.py::test_trees_to_dataframe",
    "test_consistency.py::test_cli_train_predict_consistency",
    "test_refit.py::test_refit_changes_leaf_values_toward_new_labels",
    "test_booster.py::test_dart",
    "test_booster.py::test_goss_trains",
    "test_sparse.py::test_sparse_training_matches_dense",
    # bench-scale streaming-prediction A/B (500k rows); the <=5k-row parity
    # tests in test_streaming_predict.py stay tier-1
    "test_streaming_predict.py::test_500k_prediction_ab_chunked_vs_singleshot",
    "test_dask.py::test_dask_distributed_predict_matches_local",
    # round-21 launch-scan battery: each variant keeps its cheaper N in the
    # default tier; the duplicate scan length, the mesh/fleet compositions
    # (also exercised by the perf-gate launch scenario and the
    # tools/run_tests.sh N=1-vs-N=2 smoke) move here
    "test_launch_scan.py::test_launch_parity[2-bagging]",
    "test_launch_scan.py::test_launch_parity[2-bagging_freq2]",
    "test_launch_scan.py::test_launch_parity[2-goss]",
    "test_launch_scan.py::test_launch_parity[2-feature_fraction]",
    "test_launch_scan.py::test_launch_parity[2-extra_trees]",
    "test_launch_scan.py::test_launch_parity[2-multiclass]",
    "test_launch_scan.py::test_launch_parity_mesh_data_parallel",
    "test_launch_scan.py::test_launch_parity_fleet",
}


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest

    on_tpu = False
    if _NATIVE_RUN:
        # time-box the device probe: the axon tunnel can be down for hours
        # and jax.devices() blocks inside backend init, which would hang
        # collection of the whole suite
        from concurrent.futures import ThreadPoolExecutor

        try:
            with ThreadPoolExecutor(max_workers=1) as ex:
                devs = ex.submit(jax.devices).result(timeout=60)
            on_tpu = any(d.platform == "tpu" for d in devs)
        except Exception:
            on_tpu = False
    skip_native = _pytest.mark.skip(
        reason="needs a real TPU (set LGBM_TPU_NATIVE=1 with hardware attached)"
    )
    for item in items:
        rel = item.nodeid.split("/")[-1]
        base = rel.split("[")[0]
        if rel in _SLOW_TESTS or base in _SLOW_TESTS:
            item.add_marker(_pytest.mark.slow)
        if "native_tpu" in item.keywords and not on_tpu:
            item.add_marker(skip_native)


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
