"""Test config: run on a virtual 8-device CPU mesh.

Multi-chip hardware isn't available in CI; sharding tests run over
``--xla_force_host_platform_device_count=8`` as the reference's distributed
tests run N CLI processes on localhost (tests/distributed/_test_distributed.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
