"""Test config: make an 8-device virtual CPU mesh available.

Multi-chip hardware isn't available in CI; sharding tests run over
``--xla_force_host_platform_device_count=8`` as the reference's distributed
tests run N CLI processes on localhost (tests/distributed/_test_distributed.py).

jax may already be imported (sitecustomize preloads the TPU tunnel), so the
flag is injected before the FIRST CPU client creation — the CPU backend is
lazy, which keeps this effective; tests that need the mesh use
``jax.devices("cpu")`` explicitly.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Run the whole suite on the virtual CPU mesh: correctness tests don't need
# the (remote-tunneled, slow-compile) TPU, and serial-vs-sharded comparisons
# must run on ONE platform so reduction-order diffs don't flip tied splits.
os.environ["JAX_PLATFORMS"] = "cpu"

# The env var alone is NOT enough: a TPU-tunnel shim (sitecustomize) may have
# already set the jax_platforms CONFIG to prefer its backend, which overrides
# the env and routes every default-placed op through the tunnel (and hangs the
# whole suite if the tunnel is down). Force the config before any backend
# initializes — jax may be imported, but its backends are still lazy here.
import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
