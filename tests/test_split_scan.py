"""Parity tests for the fused best-split scan kernel
(ops/pallas/split_scan.py) against the XLA best_split oracle — interpret
mode everywhere; the AOT Mosaic compile check lives in test_aot_mosaic.py.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from lightgbm_tpu.ops.pallas.split_scan import fused_best_split  # noqa: E402
from lightgbm_tpu.ops.split import best_split  # noqa: E402


def _leaf_problem(n, f, b, seed=0, nan_frac=0.0):
    rng = np.random.default_rng(seed)
    num_bins = rng.integers(max(3, b // 2), b + 1, size=f).astype(np.int32)
    nan_bins = np.full(f, -1, np.int32)
    if nan_frac > 0:
        which = rng.random(f) < nan_frac
        nan_bins[which] = num_bins[which] - 1
    hist = np.zeros((f, b, 3), np.float32)
    for j in range(f):
        bins = rng.integers(0, num_bins[j], size=n)
        g = rng.normal(size=n).astype(np.float32)
        h = (rng.random(n).astype(np.float32) + 0.1)
        np.add.at(hist[j, :, 0], bins, g)
        np.add.at(hist[j, :, 1], bins, h)
        np.add.at(hist[j, :, 2], bins, 1.0)
    # per-feature histograms describe the same rows, so parent stats must be
    # one feature's totals (use feature 0, and overwrite the others' totals
    # scale to match is unnecessary for split parity — the oracle gets the
    # identical tensors)
    parent = hist[0].sum(axis=0)
    return hist, parent, num_bins, nan_bins


HYPER = [
    dict(lambda_l1=0.0, lambda_l2=0.01, min_data_in_leaf=5,
         min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0),
    dict(lambda_l1=0.3, lambda_l2=1.0, min_data_in_leaf=40,
         min_sum_hessian_in_leaf=2.0, min_gain_to_split=0.1),
]


@pytest.mark.parametrize("hp", HYPER)
@pytest.mark.parametrize("n,f,b,nan_frac", [
    (4000, 12, 64, 0.0),
    (4000, 28, 256, 0.5),
    (900, 5, 17, 1.0),  # ragged bin count, every feature has a NaN bin
    (50, 3, 8, 0.0),  # tiny leaf: min_data gates most candidates
])
def test_fused_matches_best_split(hp, n, f, b, nan_frac):
    hist, parent, num_bins, nan_bins = _leaf_problem(
        n, f, b, seed=n + f, nan_frac=nan_frac
    )
    mask = jnp.ones((f,), bool)
    want = best_split(
        jnp.asarray(hist), parent[0], parent[1], parent[2],
        jnp.asarray(num_bins), jnp.asarray(nan_bins), mask, **hp,
    )
    got = fused_best_split(
        jnp.asarray(hist), parent[0], parent[1], parent[2],
        jnp.asarray(num_bins), jnp.asarray(nan_bins), mask,
        interpret=True, **hp,
    )
    if not np.isfinite(float(want.gain)):
        assert not np.isfinite(float(got.gain))
        return
    assert int(got.feature) == int(want.feature)
    assert int(got.bin) == int(want.bin)
    assert bool(got.default_left) == bool(want.default_left)
    # both engines run f32; near-edge thresholds amplify the parent-minus-
    # left cancellation in BOTH (each lands ~1e-3 from the f64 truth on the
    # worst synthetic features), so gains compare at that scale while the
    # discrete choices above must be identical
    np.testing.assert_allclose(float(got.gain), float(want.gain), rtol=5e-3,
                               atol=1e-4)
    np.testing.assert_allclose(float(got.left_g), float(want.left_g),
                               rtol=1e-4, atol=1e-4)
    assert float(got.left_cnt) == float(want.left_cnt)  # exact digit cumsum


def test_fused_no_valid_split_returns_neg_inf():
    hist, parent, num_bins, nan_bins = _leaf_problem(30, 4, 16, seed=2)
    got = fused_best_split(
        jnp.asarray(hist), parent[0], parent[1], parent[2],
        jnp.asarray(num_bins), jnp.asarray(nan_bins),
        jnp.ones((4,), bool),
        lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=10_000,
        min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0,
        interpret=True,
    )
    assert not np.isfinite(float(got.gain))


def test_fused_grower_matches_default_end_to_end():
    """A tree grown with fused_split_scan (interpret hook) equals the
    default scan's tree structure on real data."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops.pallas import split_scan

    rng = np.random.default_rng(5)
    X = rng.normal(size=(3000, 10))
    X[::11, 4] = np.nan
    y = X[:, 0] + np.sin(X[:, 1]) + 0.5 * np.isnan(X[:, 4])
    base = {"objective": "regression", "verbosity": -1, "num_leaves": 31,
            "min_data_in_leaf": 20}
    b0 = lgb.train(base, lgb.Dataset(X, y, params=base), 6)
    split_scan._INTERPRET = True
    try:
        pf = {**base, "fused_split_scan": True}
        b1 = lgb.train(pf, lgb.Dataset(X, y, params=pf), 6)
    finally:
        split_scan._INTERPRET = False

    def _structure(bst):
        return [
            line for line in bst.model_to_string().splitlines()
            if line.startswith(("split_feature=", "threshold="))
        ]

    assert _structure(b0) == _structure(b1)


# --------------------------------------------------------------- near ties
# Property test bounding the fused-scan near-tie flip rate (VERDICT item
# 5): adversarial two-feature leaf histograms whose top candidates sit a
# controlled relative gain gap apart, compared across the fused scan, the
# XLA best_split, and a float64 oracle.  Both engines run f32, so below
# the parent-minus-left cancellation scale the argmax can legitimately
# pick the runner-up; the property that must hold is (a) above the scale
# the choice matches the f64 oracle exactly, and (b) below it a flip only
# ever lands on a candidate whose TRUE (f64) gain is within the gap of
# optimal — near-tie flips are benign, wrong-split flips are bugs.
#
# Measured on this construction (seeds 0..9, gap targets 1e-1..1e-6, CPU
# f32, recorded in BENCH_NOTES.md): zero flips for relative gap >= 1e-5
# (53 trials); at gap ~1e-6 each engine flips on 1 of 7 trials (~14%), and
# a wider 150-trial sweep (25 seeds) showed 3-4 of 18 trials (~20%) at
# gap <= 1e-6 — every flip landing on the f64 runner-up candidate.

_NT_L2 = 0.01
_NT_MIN_DATA = 5
_NT_MIN_HESS = 1e-3
_NT_CANCEL_SCALE = 1e-4  # relative-gap scale above which flips = bugs


def _oracle_gains64(hist64, parent):
    """f64 per-(feature, bin) split gains, engine conventions (bins <= t
    go left, t valid in [0, B-2], min_data/min_hess on both children)."""
    B = hist64.shape[1]
    cum = np.cumsum(hist64, axis=1)
    lg, lh, lc = cum[..., 0], cum[..., 1], cum[..., 2]
    rg, rh, rc = parent[0] - lg, parent[1] - lh, parent[2] - lc
    gain = lg**2 / (lh + _NT_L2 + 1e-15) + rg**2 / (rh + _NT_L2 + 1e-15)
    ok = (
        (np.arange(B)[None, :] < B - 1)
        & (lc >= _NT_MIN_DATA) & (rc >= _NT_MIN_DATA)
        & (lh >= _NT_MIN_HESS) & (rh >= _NT_MIN_HESS)
    )
    return np.where(ok, gain, -np.inf)


def _near_tie_problem(seed, target_rel_gap, n=4000, B=64):
    """Two independent histograms; feature 1's gradients are bisected to a
    scale where its f64-best gain trails feature 0's by ~target_rel_gap.
    Independence decorrelates the engines' f32 rounding (identical
    histograms round identically and can never flip)."""
    rng = np.random.default_rng(seed)

    def mk():
        bins = rng.integers(0, B, size=n)
        g = rng.normal(size=n)
        h = rng.random(n) + 0.1
        H = np.zeros((B, 3))
        np.add.at(H[:, 0], bins, g)
        np.add.at(H[:, 1], bins, h)
        np.add.at(H[:, 2], bins, 1.0)
        return H

    h0, h1 = mk(), mk()
    parent = h0.sum(axis=0)
    tgt = _oracle_gains64(h0[None], parent).max() * (1.0 - target_rel_gap)
    lo, hi = 0.0, 4.0
    for _ in range(80):
        mid = 0.5 * (lo + hi)
        hh = h1.copy()
        hh[:, 0] *= mid
        if _oracle_gains64(hh[None], parent).max() < tgt:
            lo = mid
        else:
            hi = mid
    h1[:, 0] *= 0.5 * (lo + hi)
    return np.stack([h0, h1]), parent


def test_near_tie_flip_rate_bounded():
    hp = dict(lambda_l1=0.0, lambda_l2=_NT_L2, min_data_in_leaf=_NT_MIN_DATA,
              min_sum_hessian_in_leaf=_NT_MIN_HESS, min_gain_to_split=0.0)
    B = 64
    nb = jnp.full((2,), B, jnp.int32)
    nanb = jnp.full((2,), -1, jnp.int32)
    mask = jnp.ones((2,), bool)
    below = {"xla": 0, "fused": 0, "n": 0}
    for target in (1e-1, 1e-2, 1e-3, 1e-4, 1e-5, 1e-6):
        for seed in range(10):
            hist64, parent = _near_tie_problem(seed, target)
            gain64 = _oracle_gains64(hist64, parent)
            flat = np.sort(gain64.ravel())[::-1]
            best, second = flat[0], flat[1]
            rel_gap = (best - second) / abs(best)
            fo, to = divmod(int(np.argmax(gain64.ravel())), B)
            hist32 = jnp.asarray(hist64.astype(np.float32))
            picks = {}
            w = best_split(hist32, parent[0], parent[1], parent[2],
                           nb, nanb, mask, **hp)
            picks["xla"] = (int(w.feature), int(w.bin))
            fz = fused_best_split(hist32, parent[0], parent[1], parent[2],
                                  nb, nanb, mask, interpret=True, **hp)
            picks["fused"] = (int(fz.feature), int(fz.bin))
            for eng, (pf, pb) in picks.items():
                flipped = (pf, pb) != (fo, to)
                if rel_gap >= _NT_CANCEL_SCALE:
                    assert not flipped, (
                        f"{eng} flipped ABOVE the cancellation scale: "
                        f"gap={rel_gap:.2e} picked f{pf}b{pb} over "
                        f"f{fo}b{to} (seed={seed}, target={target})"
                    )
                elif flipped:
                    below[eng] += 1
                    # benign-flip property: the pick's TRUE gain is itself
                    # within the cancellation scale of optimal
                    assert gain64[pf, pb] >= best * (1 - _NT_CANCEL_SCALE), (
                        f"{eng} flip landed on a genuinely worse split: "
                        f"{gain64[pf, pb]} vs {best}"
                    )
            if rel_gap < _NT_CANCEL_SCALE:
                below["n"] += 1
    # sub-scale flips happen (that is WHY the scale exists) but must stay
    # the exception, not the rule
    if below["n"]:
        assert below["xla"] <= below["n"] * 0.5, below
        assert below["fused"] <= below["n"] * 0.5, below


def test_with_margin_matches_oracle_gap():
    """Both engines' ``with_margin`` output tracks the f64 relative gap of
    best-vs-runner-up on well-separated problems."""
    hp = dict(lambda_l1=0.0, lambda_l2=_NT_L2, min_data_in_leaf=_NT_MIN_DATA,
              min_sum_hessian_in_leaf=_NT_MIN_HESS, min_gain_to_split=0.0)
    B = 64
    nb = jnp.full((2,), B, jnp.int32)
    nanb = jnp.full((2,), -1, jnp.int32)
    mask = jnp.ones((2,), bool)
    for seed, target in [(0, 1e-1), (3, 1e-2), (5, 1e-3)]:
        hist64, parent = _near_tie_problem(seed, target)
        gain64 = _oracle_gains64(hist64, parent)
        flat = np.sort(gain64.ravel())[::-1]
        rel_gap = (flat[0] - flat[1]) / abs(flat[0])
        hist32 = jnp.asarray(hist64.astype(np.float32))
        _, mx = best_split(hist32, parent[0], parent[1], parent[2],
                           nb, nanb, mask, with_margin=True, **hp)
        _, mf = fused_best_split(hist32, parent[0], parent[1], parent[2],
                                 nb, nanb, mask, with_margin=True,
                                 interpret=True, **hp)
        for eng, m in (("xla", float(mx)), ("fused", float(mf))):
            # margin is runner-up over EVERY candidate (bins included), so
            # it can only be <= the cross-feature gap; it must never report
            # a comfortably-separated problem as a tie nor exceed the gap
            # by more than f32 noise
            assert m <= rel_gap * 1.05 + 1e-5, (eng, m, rel_gap)
            if rel_gap > 1e-2:
                assert m > 1e-4, (eng, m, rel_gap)


# ---- int8-by-default accumulation (histogram engine v2): the near-tie
# battery for the DEFAULT path.  Rows are quantized onto the grower's
# QMAX grid (ops/quantize.hist_acc_scales), summed exactly (the i32 digit
# sums are exact), and the grower's decision flow is replayed: int8 scan
# with margin -> f32 re-accumulate when margin < near_tie_tol -> re-scan.
# The property: the FINAL pick never flips away from the f64 oracle at
# relative gain gaps >= 1e-4 (_NT_CANCEL_SCALE), and the f32 refine
# actually triggers whenever the true gap is deep inside the tolerance.
# Measured rates on this battery are recorded in BENCH_NOTES.md (round 10).

_NT_TOL = 1e-3  # GrowerParams.near_tie_tol default


def _near_tie_problem_rows(seed, target_rel_gap, n=4000, B=64):
    """Row-level variant of _near_tie_problem: returns the f64 histograms
    AND the underlying rows so the int8 path can quantize per-row (the
    real error model — per-bin error grows with the bin count)."""
    rng = np.random.default_rng(seed)

    def mk():
        bins = rng.integers(0, B, size=n)
        g = rng.normal(size=n)
        h = rng.random(n) + 0.1
        return bins, g, h

    def hist_of(bins, g, h):
        H = np.zeros((B, 3))
        np.add.at(H[:, 0], bins, g)
        np.add.at(H[:, 1], bins, h)
        np.add.at(H[:, 2], bins, 1.0)
        return H

    b0, g0, h0 = mk()
    b1, g1, h1 = mk()
    parent = hist_of(b0, g0, h0).sum(axis=0)
    tgt = _oracle_gains64(hist_of(b0, g0, h0)[None], parent).max() * (
        1.0 - target_rel_gap
    )
    lo, hi = 0.0, 4.0
    for _ in range(80):
        mid = 0.5 * (lo + hi)
        if _oracle_gains64(hist_of(b1, g1 * mid, h1)[None], parent).max() < tgt:
            lo = mid
        else:
            hi = mid
    g1 = g1 * (0.5 * (lo + hi))
    rows = [(b0, g0, h0), (b1, g1, h1)]
    hist64 = np.stack([hist_of(*r) for r in rows])
    return hist64, parent, rows


def _int8_hist(rows, B):
    """Per-row QMAX-grid quantization + exact integer bin sums — the seg
    kernels' int8-by-default accumulation, emulated in f64 (exact)."""
    from lightgbm_tpu.ops.pallas.seg import QMAX

    gs = max(max(np.abs(r[1]).max() for r in rows) / QMAX, 1e-30)
    hs = max(max(np.abs(r[2]).max() for r in rows) / QMAX, 1e-30)
    out = np.zeros((len(rows), B, 3))
    for j, (bins, g, h) in enumerate(rows):
        qg = np.clip(np.round(g / gs), -QMAX, QMAX)
        qh = np.clip(np.round(h / hs), -QMAX, QMAX)
        np.add.at(out[j, :, 0], bins, qg)
        np.add.at(out[j, :, 1], bins, qh)
        np.add.at(out[j, :, 2], bins, 1.0)
    out[:, :, 0] *= gs
    out[:, :, 1] *= hs
    return out


def test_int8_default_near_tie_zero_flips():
    hp = dict(lambda_l1=0.0, lambda_l2=_NT_L2, min_data_in_leaf=_NT_MIN_DATA,
              min_sum_hessian_in_leaf=_NT_MIN_HESS, min_gain_to_split=0.0)
    B = 64
    nb = jnp.full((2,), B, jnp.int32)
    nanb = jnp.full((2,), -1, jnp.int32)
    mask = jnp.ones((2,), bool)
    stats = {"trials": 0, "trigger": 0, "int8_flips": 0, "final_flips": 0}
    for target in (1e-1, 1e-2, 1e-3, 1e-4, 1e-5):
        for seed in range(6):
            hist64, parent, rows = _near_tie_problem_rows(seed, target)
            gain64 = _oracle_gains64(hist64, parent)
            flat = np.sort(gain64.ravel())[::-1]
            rel_gap = (flat[0] - flat[1]) / abs(flat[0])
            fo, to = divmod(int(np.argmax(gain64.ravel())), B)
            hq = _int8_hist(rows, B)
            pq = hq[0].sum(axis=0)  # grower totals come from the int8 hist
            hq32 = jnp.asarray(hq.astype(np.float32))
            h32 = jnp.asarray(hist64.astype(np.float32))
            for eng, scan in (
                ("xla", lambda *a, **k: best_split(*a, **k)),
                ("fused", lambda *a, **k: fused_best_split(
                    *a, interpret=True, **k)),
            ):
                c8, margin = scan(hq32, pq[0], pq[1], pq[2], nb, nanb, mask,
                                  with_margin=True, **hp)
                near = float(margin) < _NT_TOL
                if near:
                    # grower flow: f32 re-accumulate of the SAME window,
                    # re-scan without margin
                    cf = scan(h32, pq[0], pq[1], pq[2], nb, nanb, mask, **hp)
                    pick = (int(cf.feature), int(cf.bin))
                else:
                    pick = (int(c8.feature), int(c8.bin))
                stats["trials"] += 1
                stats["trigger"] += int(near)
                stats["int8_flips"] += int(
                    (int(c8.feature), int(c8.bin)) != (fo, to)
                )
                flipped = pick != (fo, to)
                stats["final_flips"] += int(flipped and
                                            rel_gap >= _NT_CANCEL_SCALE)
                if rel_gap >= _NT_CANCEL_SCALE:
                    # the headline property: int8-by-default NEVER changes
                    # structure when the true gap is >= 1e-4 relative
                    assert not flipped, (
                        f"int8-default {eng} flipped at gap {rel_gap:.2e}: "
                        f"picked f{pick[0]}b{pick[1]} over f{fo}b{to} "
                        f"(seed={seed}, target={target}, near={near})"
                    )
                if rel_gap < 1e-5:
                    # trigger property: deep ties MUST engage the f32
                    # refine (margin <= gap + int8 noise << near_tie_tol)
                    assert near, (
                        f"{eng}: f32 refine did not trigger at gap "
                        f"{rel_gap:.2e} (margin={float(margin):.2e})"
                    )
    assert stats["final_flips"] == 0
    assert stats["trigger"] >= 1  # the battery exercises the refine path


def test_fused_scan_inside_data_parallel_mesh():
    """The fused kernel must trace and run inside the shard_map'd
    data-parallel grower (the on-chip A/B will run it there): sharded
    fused training == serial fused == serial default on integer data."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops.pallas import split_scan

    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs the virtual CPU mesh")
    rng = np.random.default_rng(3)
    n = 4000
    X = rng.integers(0, 63, size=(n, 6)).astype(np.float64)
    y = (0.4 * X[:, 0] - 0.2 * X[:, 1] + rng.normal(scale=2.0, size=n))

    def _structure(bst):
        return [
            line for line in bst.model_to_string().splitlines()
            if line.startswith(("split_feature=", "threshold="))
        ]

    split_scan._INTERPRET = True
    try:
        base = {"objective": "regression", "verbosity": -1,
                "num_leaves": 15, "min_data_in_leaf": 20,
                "fused_split_scan": True}
        serial = lgb.train(base, lgb.Dataset(X, y, params=base), 4)
        dp = {**base, "tree_learner": "data"}
        sharded = lgb.train(dp, lgb.Dataset(X, y, params=dp), 4)
    finally:
        split_scan._INTERPRET = False
    plain = {"objective": "regression", "verbosity": -1,
             "num_leaves": 15, "min_data_in_leaf": 20}
    default = lgb.train(plain, lgb.Dataset(X, y, params=plain), 4)
    assert _structure(serial) == _structure(default)
    assert _structure(sharded) == _structure(serial)


# ---- feature_contri (reference FeatureMetainfo::penalty, ---------------
# feature_histogram.hpp:1445-1448): per-feature multiplier on the
# improvement BEFORE the cross-feature argmax, in both engines.

def _dup_hist(seed=0, n=2000, b=32):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b, size=n)
    g = rng.normal(size=n).astype(np.float32) - 0.3 * (bins > b // 2)
    h = np.ones(n, np.float32)
    hist = np.zeros((2, b, 3), np.float32)
    for j in range(2):
        np.add.at(hist[j, :, 0], bins, g)
        np.add.at(hist[j, :, 1], bins, h)
        np.add.at(hist[j, :, 2], bins, 1.0)
    parent = hist[0].sum(axis=0)
    return (
        jnp.asarray(hist), parent, jnp.full((2,), b, np.int32),
        jnp.full((2,), -1, np.int32), jnp.ones((2,), bool),
    )


_FC_HP = dict(lambda_l1=0.0, lambda_l2=0.01, min_data_in_leaf=5,
              min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0)


def test_feature_contri_flips_tied_argmax_xla():
    hist, parent, num_bins, nan_bins, mask = _dup_hist()
    base = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        **_FC_HP,
    )
    assert int(base.feature) == 0  # exact tie -> lowest index
    fc = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        feature_contri=jnp.asarray([0.5, 1.0], jnp.float32), **_FC_HP,
    )
    assert int(fc.feature) == 1
    np.testing.assert_allclose(float(fc.gain), float(base.gain), rtol=1e-6)
    # and the multiplier actually scales the reported improvement
    half = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        feature_contri=jnp.asarray([0.5, 0.5], jnp.float32), **_FC_HP,
    )
    np.testing.assert_allclose(float(half.gain), 0.5 * float(base.gain),
                               rtol=1e-5)


def test_feature_contri_flips_tied_argmax_fused():
    hist, parent, num_bins, nan_bins, mask = _dup_hist(seed=1)
    base = fused_best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        interpret=True, **_FC_HP,
    )
    assert int(base.feature) == 0
    fc = fused_best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        feature_contri=jnp.asarray([0.5, 1.0], jnp.float32),
        interpret=True, **_FC_HP,
    )
    assert int(fc.feature) == 1
    np.testing.assert_allclose(float(fc.gain), float(base.gain), rtol=1e-6)


def test_feature_contri_engines_agree():
    hist, parent, num_bins, nan_bins, mask = _dup_hist(seed=2)
    contri = jnp.asarray([0.25, 1.5], jnp.float32)
    want = best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        feature_contri=contri, **_FC_HP,
    )
    got = fused_best_split(
        hist, parent[0], parent[1], parent[2], num_bins, nan_bins, mask,
        feature_contri=contri, interpret=True, **_FC_HP,
    )
    assert int(got.feature) == int(want.feature)
    assert int(got.bin) == int(want.bin)
    np.testing.assert_allclose(float(got.gain), float(want.gain), rtol=5e-3,
                               atol=1e-4)


def test_feature_contri_e2e_moves_root_split():
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    X = rng.normal(size=(1000, 5))
    y = X[:, 0] * 1.5 - X[:, 1] + rng.normal(scale=0.1, size=1000)
    base = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
            "min_data_in_leaf": 5}
    b0 = lgb.train(base, lgb.Dataset(X, y), 1)
    assert b0.models_[0].split_feature[0] == 0
    b1 = lgb.train({**base, "feature_contri": [0.001, 1, 1, 1, 1]},
                   lgb.Dataset(X, y), 1)
    assert b1.models_[0].split_feature[0] != 0
