"""Oracle tests for the segment-resident layout (ops/pallas/seg.py) and the
sort-based partition (ops/segpart.py).

Reference semantics under test: DataPartition::Split (stable partition,
src/treelearner/data_partition.hpp:101) and DenseBin::ConstructHistogram
(src/io/dense_bin.hpp:99), via a NumPy oracle.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import leaf_histogram_segment
from lightgbm_tpu.ops.pallas.seg import (
    pack_rows,
    padded_rows,
    seg_hist,
    unpack_stats,
)
from lightgbm_tpu.ops.segpart import (
    leaf_id_from_seg,
    leaf_of_positions,
    sort_partition,
)


@pytest.fixture(scope="module")
def packed():
    rng = np.random.default_rng(7)
    f, n = 11, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )  # PLANE-MAJOR [LANES, n_pad]
    catmask = (rng.random(256) < 0.5).astype(np.float32)
    return dict(
        f=f, n=n, n_pad=n_pad, bins=bins, g=g, h=h, m=m,
        seg=seg, segnp=np.asarray(seg), catmask=catmask,
    )


def test_pack_unpack_roundtrip(packed):
    p = packed
    b2, g2, h2, m2, r2 = unpack_stats(p["seg"], p["f"], n=p["n"])
    assert np.array_equal(np.asarray(b2), p["bins"])
    assert np.array_equal(np.asarray(g2), p["g"])  # exact f32 bit transport
    assert np.array_equal(np.asarray(h2), p["h"])
    assert np.array_equal(np.asarray(m2), p["m"])
    assert np.array_equal(np.asarray(r2), np.arange(p["n"]))


def _np_partition(segnp, f, sb, cnt, feat, tbin, dl, nanb, iscat, catmask):
    rows = segnp[:, sb : sb + cnt].T  # [cnt, LANES]
    packedcol = rows[:, feat // 2].view(np.uint16).astype(np.int64)
    colv = (packedcol >> (8 * (feat % 2))) & 0xFF
    if iscat:
        gl = (catmask[np.clip(colv, 0, len(catmask) - 1)] > 0.5) & (
            colv < len(catmask)
        )
    else:
        gl = (colv <= tbin) | ((dl != 0) & (nanb >= 0) & (colv == nanb))
    return rows[gl], rows[~gl]


@pytest.mark.parametrize(
    "sb,cnt,feat,tbin,dl,nanb,iscat",
    [
        (0, 5000, 3, 120, 0, -1, 0),  # root
        (17, 3000, 5, 80, 1, 200, 0),  # unaligned begin, NaN default-left
        (1000, 37, 2, 128, 0, -1, 0),  # tiny segment
        (513, 1029, 7, 30, 0, -1, 1),  # categorical
        (5, 600, 1, 255, 0, -1, 0),  # all-left
        (9, 600, 1, -1, 0, -1, 0),  # all-right
        (4000, 1000, 10, 100, 0, -1, 0),  # tail of the array
    ],
)
def test_sort_partition_vs_oracle(packed, sb, cnt, feat, tbin, dl, nanb, iscat):
    p = packed
    seg1, nl, nr = sort_partition(
        p["seg"], jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
        jnp.int32(tbin), jnp.int32(dl), jnp.int32(nanb), jnp.int32(iscat),
        jnp.asarray(p["catmask"]), f=p["f"], n_pad=p["n_pad"],
    )
    nl, nr = int(nl), int(nr)
    expL, expR = _np_partition(
        p["segnp"], p["f"], sb, cnt, feat, tbin, dl, nanb, iscat, p["catmask"]
    )
    assert (nl, nr) == (len(expL), len(expR))
    got = np.asarray(seg1)
    assert np.array_equal(got[:, sb : sb + nl].T, expL)  # stable left
    assert np.array_equal(got[:, sb + nl : sb + cnt].T, expR)  # stable right
    assert np.array_equal(got[:, :sb], p["segnp"][:, :sb])  # neighbors
    assert np.array_equal(got[:, sb + cnt :], p["segnp"][:, sb + cnt :])


@pytest.mark.parametrize("st,cnt", [(0, 5000), (17, 3000), (513, 1029), (1000, 37)])
def test_seg_hist_vs_oracle(packed, st, cnt):
    p = packed
    hs = seg_hist(
        p["seg"], jnp.asarray([st, cnt], jnp.int32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"],
    )
    bo, go, ho, mo, _ = unpack_stats(p["seg"][:, st : st + cnt], p["f"])
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    d = np.abs(np.asarray(hs) - np.asarray(ref)).max()
    rel = d / max(1e-9, np.abs(np.asarray(ref)).max())
    assert rel < 5e-6  # three-term bf16 split: ~26-bit addends (r3)


@pytest.mark.parametrize("st,cnt", [(0, 5000), (17, 3000), (1000, 37)])
def test_seg_hist_pallas_kernel_interpret(packed, st, cnt):
    """Exercise the actual Pallas kernel body (DMA tiling, in-VMEM transpose,
    bf16 hi/lo split) in interpret mode — off-TPU the `seg_hist` dispatcher
    would otherwise route to the same reference impl the oracle uses."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas

    p = packed
    hs = seg_hist_pallas(
        p["seg"], jnp.asarray([st, cnt], jnp.int32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"], interpret=True,
    )
    bo, go, ho, mo, _ = unpack_stats(p["seg"][:, st : st + cnt], p["f"])
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    d = np.abs(np.asarray(hs) - np.asarray(ref)).max()
    rel = d / max(1e-9, np.abs(np.asarray(ref)).max())
    assert rel < 5e-6  # three-term bf16 split: ~26-bit addends (r3)


def test_leaf_mapping_roundtrip(packed):
    n = packed["n"]
    rng = np.random.default_rng(3)
    Lb = jnp.asarray([0, 1200, 700, 0], jnp.int32)
    Lr = jnp.asarray([700, n - 1200, 500, 0], jnp.int32)
    lp = np.asarray(leaf_of_positions(Lb, Lr, jnp.int32(3), n))
    assert (lp[:700] == 0).all()
    assert (lp[700:1200] == 2).all()
    assert (lp[1200:] == 1).all()
    perm = rng.permutation(n).astype(np.int32)
    lid = np.asarray(leaf_id_from_seg(jnp.asarray(perm), jnp.asarray(lp)))
    exp = np.empty(n, np.int32)
    exp[perm] = lp
    assert np.array_equal(lid, exp)


def test_seg_hist_int8_quantized_exact(packed):
    """Quantized-gradient int8 variant: grid multiples accumulate EXACTLY
    in i32 (gradient_discretizer.cpp grid), so the kernel must match the
    f32 oracle bit-for-bit at these magnitudes."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas

    p = packed
    rng = np.random.default_rng(13)
    gs, hs = np.float32(0.037), np.float32(0.0021)
    kq = rng.integers(-63, 64, size=p["n"]).astype(np.float32)
    hq = rng.integers(0, 64, size=p["n"]).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(p["bins"]),
        jnp.asarray(kq * gs),
        jnp.asarray(hq * hs),
        jnp.asarray(p["m"]),
        p["n_pad"],
    )
    hs_out = seg_hist_pallas(
        seg, jnp.asarray([17, 3000], jnp.int32),
        jnp.asarray([gs, hs], jnp.float32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"],
        quantized=True, interpret=True,
    )
    bo, go, ho, mo, _ = unpack_stats(seg[:, 17 : 17 + 3000], p["f"])
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    got = np.asarray(hs_out)
    # counts exact; g/h equal to the integer sums times the scales
    assert np.array_equal(got[:, :, 2], np.asarray(ref)[:, :, 2])
    assert np.allclose(got, np.asarray(ref), rtol=1e-6, atol=1e-6)
