"""Oracle tests for the segment-resident layout (ops/pallas/seg.py) and the
sort-based partition (ops/segpart.py).

Reference semantics under test: DataPartition::Split (stable partition,
src/treelearner/data_partition.hpp:101) and DenseBin::ConstructHistogram
(src/io/dense_bin.hpp:99), via a NumPy oracle.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import leaf_histogram_segment
from lightgbm_tpu.ops.pallas.seg import (
    pack_rows,
    padded_rows,
    seg_hist,
    unpack_stats,
)
from lightgbm_tpu.ops.segpart import (
    leaf_id_from_seg,
    leaf_of_positions,
    sort_partition,
)


@pytest.fixture(scope="module")
def packed():
    rng = np.random.default_rng(7)
    f, n = 11, 5000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m), n_pad
    )  # PLANE-MAJOR [LANES, n_pad]
    catmask = (rng.random(256) < 0.5).astype(np.float32)
    return dict(
        f=f, n=n, n_pad=n_pad, bins=bins, g=g, h=h, m=m,
        seg=seg, segnp=np.asarray(seg), catmask=catmask,
    )


def test_pack_unpack_roundtrip(packed):
    p = packed
    b2, g2, h2, m2, r2 = unpack_stats(p["seg"], p["f"], n=p["n"])
    assert np.array_equal(np.asarray(b2), p["bins"])
    assert np.array_equal(np.asarray(g2), p["g"])  # exact f32 bit transport
    assert np.array_equal(np.asarray(h2), p["h"])
    assert np.array_equal(np.asarray(m2), p["m"])
    assert np.array_equal(np.asarray(r2), np.arange(p["n"]))


def _np_partition(segnp, f, sb, cnt, feat, tbin, dl, nanb, iscat, catmask):
    rows = segnp[:, sb : sb + cnt].T  # [cnt, LANES]
    packedcol = rows[:, feat // 2].view(np.uint16).astype(np.int64)
    colv = (packedcol >> (8 * (feat % 2))) & 0xFF
    if iscat:
        gl = (catmask[np.clip(colv, 0, len(catmask) - 1)] > 0.5) & (
            colv < len(catmask)
        )
    else:
        gl = (colv <= tbin) | ((dl != 0) & (nanb >= 0) & (colv == nanb))
    return rows[gl], rows[~gl]


@pytest.mark.parametrize(
    "sb,cnt,feat,tbin,dl,nanb,iscat",
    [
        (0, 5000, 3, 120, 0, -1, 0),  # root
        (17, 3000, 5, 80, 1, 200, 0),  # unaligned begin, NaN default-left
        (1000, 37, 2, 128, 0, -1, 0),  # tiny segment
        (513, 1029, 7, 30, 0, -1, 1),  # categorical
        (5, 600, 1, 255, 0, -1, 0),  # all-left
        (9, 600, 1, -1, 0, -1, 0),  # all-right
        (4000, 1000, 10, 100, 0, -1, 0),  # tail of the array
    ],
)
def test_sort_partition_vs_oracle(packed, sb, cnt, feat, tbin, dl, nanb, iscat):
    p = packed
    seg1, nl, nr = sort_partition(
        p["seg"], jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
        jnp.int32(tbin), jnp.int32(dl), jnp.int32(nanb), jnp.int32(iscat),
        jnp.asarray(p["catmask"]), f=p["f"], n_pad=p["n_pad"],
    )
    nl, nr = int(nl), int(nr)
    expL, expR = _np_partition(
        p["segnp"], p["f"], sb, cnt, feat, tbin, dl, nanb, iscat, p["catmask"]
    )
    assert (nl, nr) == (len(expL), len(expR))
    got = np.asarray(seg1)
    assert np.array_equal(got[:, sb : sb + nl].T, expL)  # stable left
    assert np.array_equal(got[:, sb + nl : sb + cnt].T, expR)  # stable right
    assert np.array_equal(got[:, :sb], p["segnp"][:, :sb])  # neighbors
    assert np.array_equal(got[:, sb + cnt :], p["segnp"][:, sb + cnt :])


@pytest.mark.parametrize("st,cnt", [(0, 5000), (17, 3000), (513, 1029), (1000, 37)])
def test_seg_hist_vs_oracle(packed, st, cnt):
    p = packed
    hs = seg_hist(
        p["seg"], jnp.asarray([st, cnt], jnp.int32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"],
    )
    bo, go, ho, mo, _ = unpack_stats(p["seg"][:, st : st + cnt], p["f"])
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    d = np.abs(np.asarray(hs) - np.asarray(ref)).max()
    rel = d / max(1e-9, np.abs(np.asarray(ref)).max())
    assert rel < 5e-6  # three-term bf16 split: ~26-bit addends (r3)


@pytest.mark.parametrize("st,cnt", [(0, 5000), (17, 3000), (1000, 37)])
def test_seg_hist_pallas_kernel_interpret(packed, st, cnt):
    """Exercise the actual Pallas kernel body (DMA tiling, in-VMEM transpose,
    bf16 hi/lo split) in interpret mode — off-TPU the `seg_hist` dispatcher
    would otherwise route to the same reference impl the oracle uses."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas

    p = packed
    hs = seg_hist_pallas(
        p["seg"], jnp.asarray([st, cnt], jnp.int32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"], interpret=True,
    )
    bo, go, ho, mo, _ = unpack_stats(p["seg"][:, st : st + cnt], p["f"])
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    d = np.abs(np.asarray(hs) - np.asarray(ref)).max()
    rel = d / max(1e-9, np.abs(np.asarray(ref)).max())
    assert rel < 5e-6  # three-term bf16 split: ~26-bit addends (r3)


def test_leaf_mapping_roundtrip(packed):
    n = packed["n"]
    rng = np.random.default_rng(3)
    Lb = jnp.asarray([0, 1200, 700, 0], jnp.int32)
    Lr = jnp.asarray([700, n - 1200, 500, 0], jnp.int32)
    lp = np.asarray(leaf_of_positions(Lb, Lr, jnp.int32(3), n))
    assert (lp[:700] == 0).all()
    assert (lp[700:1200] == 2).all()
    assert (lp[1200:] == 1).all()
    perm = rng.permutation(n).astype(np.int32)
    lid = np.asarray(leaf_id_from_seg(jnp.asarray(perm), jnp.asarray(lp)))
    exp = np.empty(n, np.int32)
    exp[perm] = lp
    assert np.array_equal(lid, exp)


def test_seg_hist_int8_quantized_exact(packed):
    """Quantized-gradient int8 variant: grid multiples accumulate EXACTLY
    in i32 (gradient_discretizer.cpp grid), so the kernel must match the
    f32 oracle bit-for-bit at these magnitudes."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas

    p = packed
    rng = np.random.default_rng(13)
    gs, hs = np.float32(0.037), np.float32(0.0021)
    kq = rng.integers(-63, 64, size=p["n"]).astype(np.float32)
    hq = rng.integers(0, 64, size=p["n"]).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(p["bins"]),
        jnp.asarray(kq * gs),
        jnp.asarray(hq * hs),
        jnp.asarray(p["m"]),
        p["n_pad"],
    )
    hs_out = seg_hist_pallas(
        seg, jnp.asarray([17, 3000], jnp.int32),
        jnp.asarray([gs, hs], jnp.float32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"],
        quantized=True, interpret=True,
    )
    bo, go, ho, mo, _ = unpack_stats(seg[:, 17 : 17 + 3000], p["f"])
    ref = leaf_histogram_segment(bo, go, ho, mo, 256)
    got = np.asarray(hs_out)
    # counts exact; g/h equal to the integer sums times the scales
    assert np.array_equal(got[:, :, 2], np.asarray(ref)[:, :, 2])
    assert np.allclose(got, np.asarray(ref), rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# wide (u16) bin planes — max_bin > 256 (reference DenseBin<uint16_t>,
# src/io/dense_bin.hpp:18)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def packed_wide():
    rng = np.random.default_rng(17)
    f, n, b = 5, 3000, 1000
    n_pad = padded_rows(n)
    bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        n_pad, wide=True,
    )
    catmask = (rng.random(b) < 0.5).astype(np.float32)
    return dict(
        f=f, n=n, b=b, n_pad=n_pad, bins=bins, g=g, h=h, m=m,
        seg=seg, segnp=np.asarray(seg), catmask=catmask,
    )


def test_wide_pack_unpack_roundtrip(packed_wide):
    p = packed_wide
    b2, g2, h2, m2, r2 = unpack_stats(p["seg"], p["f"], n=p["n"], wide=True)
    assert np.array_equal(np.asarray(b2), p["bins"])
    assert np.array_equal(np.asarray(g2), p["g"])
    assert np.array_equal(np.asarray(h2), p["h"])
    assert np.array_equal(np.asarray(m2), p["m"])
    assert np.array_equal(np.asarray(r2), np.arange(p["n"]))


def _np_partition_wide(segnp, sb, cnt, feat, tbin, dl, nanb, iscat, catmask):
    rows = segnp[:, sb : sb + cnt].T  # [cnt, LANES]
    colv = rows[:, feat].view(np.uint16).astype(np.int64)
    if iscat:
        gl = (catmask[np.clip(colv, 0, len(catmask) - 1)] > 0.5) & (
            colv < len(catmask)
        )
    else:
        gl = (colv <= tbin) | ((dl != 0) & (nanb >= 0) & (colv == nanb))
    return rows[gl], rows[~gl]


@pytest.mark.parametrize(
    "sb,cnt,feat,tbin,dl,nanb,iscat",
    [
        (0, 3000, 3, 500, 0, -1, 0),  # root, threshold past 256
        (17, 2000, 1, 700, 1, 900, 0),  # unaligned, NaN bin > 256
        (513, 777, 2, 300, 0, -1, 1),  # categorical, wide mask
        (100, 500, 0, 90, 0, -1, 0),  # low threshold
    ],
)
def test_wide_sort_partition_vs_oracle(
    packed_wide, sb, cnt, feat, tbin, dl, nanb, iscat
):
    p = packed_wide
    seg1, nl, nr = sort_partition(
        p["seg"], jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
        jnp.int32(tbin), jnp.int32(dl), jnp.int32(nanb), jnp.int32(iscat),
        jnp.asarray(p["catmask"]), f=p["f"], n_pad=p["n_pad"], wide=True,
    )
    nl, nr = int(nl), int(nr)
    expL, expR = _np_partition_wide(
        p["segnp"], sb, cnt, feat, tbin, dl, nanb, iscat, p["catmask"]
    )
    assert (nl, nr) == (len(expL), len(expR))
    got = np.asarray(seg1)
    assert np.array_equal(got[:, sb : sb + nl].T, expL)
    assert np.array_equal(got[:, sb + nl : sb + cnt].T, expR)
    assert np.array_equal(got[:, :sb], p["segnp"][:, :sb])
    assert np.array_equal(got[:, sb + cnt :], p["segnp"][:, sb + cnt :])


@pytest.mark.parametrize("st,cnt", [(0, 3000), (17, 2000)])
def test_wide_seg_hist_vs_oracle(packed_wide, st, cnt):
    p = packed_wide
    hs = seg_hist(
        p["seg"], jnp.asarray([st, cnt], jnp.int32),
        f=p["f"], num_bins=p["b"], n_pad=p["n_pad"], wide=True,
    )
    bo, go, ho, mo, _ = unpack_stats(
        p["seg"][:, st : st + cnt], p["f"], wide=True
    )
    ref = leaf_histogram_segment(bo, go, ho, mo, p["b"])
    d = np.abs(np.asarray(hs) - np.asarray(ref)).max()
    rel = d / max(1e-9, np.abs(np.asarray(ref)).max())
    assert rel < 5e-6


def test_wide_seg_hist_pallas_kernel_interpret(packed_wide):
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas

    p = packed_wide
    st, cnt = 17, 1500
    hs = seg_hist_pallas(
        p["seg"], jnp.asarray([st, cnt], jnp.int32),
        f=p["f"], num_bins=p["b"], n_pad=p["n_pad"], wide=True,
        interpret=True,
    )
    bo, go, ho, mo, _ = unpack_stats(
        p["seg"][:, st : st + cnt], p["f"], wide=True
    )
    ref = leaf_histogram_segment(bo, go, ho, mo, p["b"])
    d = np.abs(np.asarray(hs) - np.asarray(ref)).max()
    rel = d / max(1e-9, np.abs(np.asarray(ref)).max())
    assert rel < 5e-6


def test_wide_partition_kernel_interpret(packed_wide):
    """The Pallas streaming partition on wide planes must match the XLA
    sort path bit-for-bit (the byte-split one-hot compaction is content
    agnostic; only the key extraction reads u16)."""
    from lightgbm_tpu.ops.pallas.partition import seg_partition_pallas
    from lightgbm_tpu.ops.segpart import sort_partition_xla

    p = packed_wide
    sb, cnt, feat, tbin = 17, 2000, 1, 700
    bm = len(p["catmask"])
    bmt = max(256, -(-bm // 128) * 128)
    catm = jnp.zeros((1, bmt), jnp.float32).at[0, :bm].set(
        jnp.asarray(p["catmask"])
    )
    scal = jnp.asarray([sb, cnt, feat, tbin, 1, 900, 0, 0], jnp.int32)
    got, nl_k = seg_partition_pallas(
        p["seg"], scal, catm, f=p["f"], n_pad=p["n_pad"], use_cat=True,
        wide=True, interpret=True,
    )
    want, nl_s, _ = sort_partition_xla(
        p["seg"], jnp.int32(sb), jnp.int32(cnt), jnp.int32(feat),
        jnp.int32(tbin), jnp.int32(1), jnp.int32(900), jnp.int32(0),
        jnp.asarray(p["catmask"]), f=p["f"], n_pad=p["n_pad"], wide=True,
    )
    assert int(nl_k) == int(nl_s)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_wide_grow_tree_matches_ordered():
    """End-to-end: a seg-mode tree at max_bin=1024 equals the ordered-mode
    tree (same splits, same leaf values)."""
    from lightgbm_tpu.ops.grower import GrowerParams, grow_tree

    rng = np.random.default_rng(23)
    n, f, b = 4000, 4, 1024
    bins = rng.integers(0, b, size=(n, f)).astype(np.int32)
    grad = rng.normal(size=n).astype(np.float32)
    hess = (rng.random(n).astype(np.float32) + 0.5)
    num_bins = jnp.full((f,), b, jnp.int32)
    nan_bins = jnp.full((f,), -1, jnp.int32)
    trees = {}
    for mode in ("seg", "ordered"):
        params = GrowerParams(
            num_leaves=15, max_bin=b, min_data_in_leaf=5,
            min_sum_hessian_in_leaf=0.0, lambda_l2=0.1, hist_mode=mode,
        )
        tree, leaf_id = grow_tree(
            jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.ones(n, jnp.float32), num_bins, nan_bins,
            jnp.ones(f, bool), params,
        )
        trees[mode] = (tree, np.asarray(leaf_id))
    ts, tord = trees["seg"][0], trees["ordered"][0]
    assert int(ts.num_leaves) == int(tord.num_leaves)
    np.testing.assert_array_equal(
        np.asarray(ts.split_feature), np.asarray(tord.split_feature)
    )
    np.testing.assert_array_equal(
        np.asarray(ts.split_bin), np.asarray(tord.split_bin)
    )
    np.testing.assert_allclose(
        np.asarray(ts.leaf_value), np.asarray(tord.leaf_value), rtol=1e-5,
        atol=1e-7,
    )
    np.testing.assert_array_equal(trees["seg"][1], trees["ordered"][1])


def test_seg_vmem_gate():
    from lightgbm_tpu.ops.pallas.seg import seg_vmem_ok

    assert seg_vmem_ok(28, 256)  # the bench config always fits
    assert seg_vmem_ok(121, 1024)  # wide, moderate
    # plane-tiled grid (histogram engine v2): the accumulator/one-hot
    # scratch is sized per feature-GROUP, not per full feature set, so the
    # old 18 MB full-F accumulator shape now fits comfortably
    assert seg_vmem_ok(100, 4096)
    assert not seg_vmem_ok(121, 65536)
    assert not seg_vmem_ok(4, 65536, has_cat=True)  # cat one-hot blows up


def test_wide_seg_hist_int8_quantized(packed_wide):
    """wide (u16) planes + int8 grid accumulation together: counts exact,
    g/h equal to integer sums times the grid scales."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas

    p = packed_wide
    rng = np.random.default_rng(29)
    gs, hs = np.float32(0.041), np.float32(0.003)
    kq = rng.integers(-63, 64, size=p["n"]).astype(np.float32)
    hq = rng.integers(0, 64, size=p["n"]).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(p["bins"]), jnp.asarray(kq * gs), jnp.asarray(hq * hs),
        jnp.asarray(p["m"]), p["n_pad"], wide=True,
    )
    out = seg_hist_pallas(
        seg, jnp.asarray([17, 1500], jnp.int32),
        jnp.asarray([gs, hs], jnp.float32),
        f=p["f"], num_bins=p["b"], n_pad=p["n_pad"],
        quantized=True, wide=True, interpret=True,
    )
    bo, go, ho, mo, _ = unpack_stats(seg[:, 17:17 + 1500], p["f"], wide=True)
    ref = leaf_histogram_segment(bo, go, ho, mo, p["b"])
    got = np.asarray(out)
    assert np.array_equal(got[:, :, 2], np.asarray(ref)[:, :, 2])
    assert np.allclose(got, np.asarray(ref), rtol=1e-6, atol=1e-6)


def test_seg_hist_pallas_batch_interpret(packed):
    """K-program batched histogram launch == K serial kernel results,
    including a zero-cnt member (all-zero histogram)."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas, seg_hist_pallas_batch

    p = packed
    windows = [(0, 1500), (1500, 1000), (2500, 0), (2600, 2400)]
    scal_k = jnp.asarray(windows, jnp.int32)
    got = seg_hist_pallas_batch(
        p["seg"], scal_k, f=p["f"], num_bins=256, n_pad=p["n_pad"],
        interpret=True,
    )
    assert got.shape[0] == len(windows)
    for i, (st, cnt) in enumerate(windows):
        want = seg_hist_pallas(
            p["seg"], jnp.asarray([st, cnt], jnp.int32),
            f=p["f"], num_bins=256, n_pad=p["n_pad"], interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(want))


def test_seg_hist_batch_dispatch_cpu(packed):
    """Off-TPU dispatch: seg_hist_batch == vmapped serial seg_hist."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_batch

    p = packed
    windows = [(0, 2000), (2000, 3000)]
    scal_k = jnp.asarray(windows, jnp.int32)
    got = seg_hist_batch(
        p["seg"], scal_k, f=p["f"], num_bins=256, n_pad=p["n_pad"]
    )
    for i, (st, cnt) in enumerate(windows):
        want = seg_hist(
            p["seg"], jnp.asarray([st, cnt], jnp.int32),
            f=p["f"], num_bins=256, n_pad=p["n_pad"],
        )
        np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(want))


def test_seg_hist_int8_default_error_bound(packed):
    """int8-by-default accumulation on TRUE f32 gradients: per-bin error is
    bounded by the grid's rounding budget (cnt * scale / 2 per stat — each
    row contributes at most half a quantization step; the i32 digit sums
    themselves are exact)."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_pallas
    from lightgbm_tpu.ops.quantize import hist_acc_scales

    p = packed
    gs, hs = hist_acc_scales(
        jnp.asarray(p["g"]), jnp.asarray(p["h"]), jnp.asarray(p["m"])
    )
    got = np.asarray(seg_hist_pallas(
        p["seg"], jnp.asarray([17, 3000], jnp.int32),
        jnp.stack([gs, hs]),
        f=p["f"], num_bins=256, n_pad=p["n_pad"],
        quantized=True, interpret=True,
    ))
    bo, go, ho, mo, _ = unpack_stats(p["seg"][:, 17:17 + 3000], p["f"])
    ref = np.asarray(leaf_histogram_segment(bo, go, ho, mo, 256))
    cnt = ref[:, :, 2]
    assert np.array_equal(got[:, :, 2], cnt)  # counts are exact
    assert (np.abs(got[:, :, 0] - ref[:, :, 0])
            <= 0.5 * float(gs) * cnt + 1e-6).all()
    assert (np.abs(got[:, :, 1] - ref[:, :, 1])
            <= 0.5 * float(hs) * cnt + 1e-6).all()


def test_seg_hist_live_plane_skip_interpret(packed):
    """Dead plane groups under ``live`` come back all-zero while live
    groups are untouched; group 0 carries the totals so the grower always
    forces it live."""
    from lightgbm_tpu.ops.pallas.seg import (
        hist_bpad, hist_group, hist_ngroups, seg_hist_pallas,
    )

    p = packed
    bpad = hist_bpad(256)
    gb = hist_group(p["f"], bpad)
    ng = hist_ngroups(p["f"], bpad)
    assert ng > 1  # 11 features at bpad 256 -> 2 groups of 8
    full = np.asarray(seg_hist_pallas(
        p["seg"], jnp.asarray([17, 3000], jnp.int32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"], interpret=True,
    ))
    live = jnp.zeros((ng,), jnp.int32).at[0].set(1)
    got = np.asarray(seg_hist_pallas(
        p["seg"], jnp.asarray([17, 3000], jnp.int32), live=live,
        f=p["f"], num_bins=256, n_pad=p["n_pad"], interpret=True,
    ))
    np.testing.assert_array_equal(got[:gb], full[:gb])  # live group intact
    assert (got[gb:] == 0.0).all()  # dead group fully skipped
    all_live = np.asarray(seg_hist_pallas(
        p["seg"], jnp.asarray([17, 3000], jnp.int32),
        live=jnp.ones((ng,), jnp.int32),
        f=p["f"], num_bins=256, n_pad=p["n_pad"], interpret=True,
    ))
    np.testing.assert_array_equal(all_live, full)


@pytest.fixture(scope="module")
def packed_big():
    """Above the CPU windowing threshold (32*TILE rows)."""
    rng = np.random.default_rng(41)
    f, n = 3, 40000
    n_pad = padded_rows(n)
    bins = rng.integers(0, 256, size=(n, f)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.5
    m = (rng.random(n) < 0.8).astype(np.float32)
    seg = pack_rows(
        jnp.asarray(bins), jnp.asarray(g), jnp.asarray(h), jnp.asarray(m),
        n_pad,
    )
    return dict(f=f, n=n, n_pad=n_pad, seg=seg)


@pytest.mark.parametrize("st,cnt", [(0, 40000), (7000, 300), (33000, 6500)])
def test_seg_hist_cpu_windowed_parity(packed_big, st, cnt):
    """The capacity-bucketed windowed CPU pass == the full masked pass for
    aligned and unaligned windows across capacity rungs."""
    from lightgbm_tpu.ops.pallas.seg import (
        _CPU_WINDOW_ROWS, seg_hist_ref,
    )

    p = packed_big
    assert p["n_pad"] > _CPU_WINDOW_ROWS
    scal = jnp.asarray([st, cnt], jnp.int32)
    got = seg_hist(
        p["seg"], scal, f=p["f"], num_bins=256, n_pad=p["n_pad"]
    )
    want = seg_hist_ref(
        p["seg"], scal, f=p["f"], num_bins=256, n_pad=p["n_pad"]
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-4
    )
    # counts must be exact (integral sums of the same values)
    np.testing.assert_array_equal(
        np.asarray(got)[:, :, 2], np.asarray(want)[:, :, 2]
    )


def test_seg_hist_batch_cpu_windowed(packed_big):
    """Batched off-TPU dispatch above the windowing threshold: per-member
    capacity buckets (python loop) == serial windowed calls."""
    from lightgbm_tpu.ops.pallas.seg import seg_hist_batch

    p = packed_big
    windows = [(0, 30000), (30000, 0), (31000, 5000)]
    scal_k = jnp.asarray(windows, jnp.int32)
    got = seg_hist_batch(
        p["seg"], scal_k, f=p["f"], num_bins=256, n_pad=p["n_pad"]
    )
    for i, (st, cnt) in enumerate(windows):
        want = seg_hist(
            p["seg"], jnp.asarray([st, cnt], jnp.int32),
            f=p["f"], num_bins=256, n_pad=p["n_pad"],
        )
        np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(want))
