"""graftlint (lightgbm_tpu.lint) — the static-analysis CI gate.

Contracts under test:
  * every rule (GL001..GL010) FIRES on a seeded positive fixture and stays
    SILENT on the matching negative — the linter is pure ast, so fixtures
    are throwaway source trees written to tmp_path and never imported;
  * per-line ``# graftlint: disable[=CODES]`` suppression works and is
    rule-scoped;
  * the baseline round-trips: new findings fail the run, ``write_baseline``
    absorbs them, entries that stop firing go STALE and fail the run (a
    baseline may only shrink through review);
  * TaintWalker follows ``*args``/``**kwargs`` forwarding (and positional
    overflow into a bare ``*args``) — the GL003/GL010 call-graph gap;
  * mutation battery: re-seeding known bug shapes into copies of the REAL
    modules is caught by exactly the intended rule — the PR-3/PR-6
    aliased-ref read (GL002 on ops/pallas/partition.py), a one-sided psum
    in a lax.cond branch (GL007 on ops/grower.py), an axis_name literal
    mismatch (GL008 on ops/grower.py), and a dropped static_argnames
    entry (GL009 on ops/quantize.py);
  * the real tree is CLEAN against the committed lint_baseline.json and a
    full run fits the 6 s budget (it is a hard gate in tools/run_tests.sh).
"""

import json
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from lightgbm_tpu.lint import (
    RULES,
    load_baseline,
    run_lint,
    write_baseline,
)
from lightgbm_tpu.lint.core import IR_RULE_CODES

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "lightgbm_tpu"


def make_project(tmp_path, files, name="fixpkg"):
    """Write a throwaway package tree and return its root."""
    root = tmp_path / name
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return root


def by_rule(result, rule):
    return [f for f in result.findings if f.rule == rule]


def idents(result, rule):
    return {f.ident for f in by_rule(result, rule)}


# ===================================================================== GL001
def test_gl001_flags_every_bare_jit_reference(tmp_path):
    """Call form, assignment form, and decorator form all fire; the ident
    is the enclosing function, so the baseline key survives line churn."""
    root = make_project(tmp_path, {
        "app.py": """\
            import jax

            j = jax.pmap

            def build(fn):
                return jax.jit(fn)

            @jax.jit
            def decorated(x):
                return x
            """,
    })
    res = run_lint(root)
    assert idents(res, "GL001") == {"<module>", "build", "decorated"}
    assert not res.ok  # no baseline: every finding is new -> gate fails


def test_gl001_silent_on_instrumented_jit_and_inside_wrapper_module(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            from .obs.jit import instrumented_jit

            @instrumented_jit
            def f(x):
                return x
            """,
        "obs/__init__.py": "",
        "obs/jit.py": """\
            import jax

            def instrumented_jit(fun, **kw):
                return jax.jit(fun, **kw)
            """,
    })
    assert by_rule(run_lint(root), "GL001") == []


# ===================================================================== GL002
_GL002_KERNEL = """\
    from jax.experimental import pallas as pl

    def _kern(x_ref, o_ref):
        o_ref[...] = {read} + 1.0

    def launch(x):
        return pl.pallas_call(
            _kern,
            out_shape=x,
            input_output_aliases={{0: 0}},
        )(x)
    """


def test_gl002_flags_direct_read_of_input_aliased_ref(tmp_path):
    root = make_project(
        tmp_path, {"k.py": _GL002_KERNEL.format(read="x_ref[...]")}
    )
    assert idents(run_lint(root), "GL002") == {"_kern:_kern:x_ref"}


def test_gl002_silent_on_output_ref_and_derived_values(tmp_path):
    """Reading the OUTPUT alias is the fix; subscripting a value that came
    FROM the ref is not a ref read (value taint is GL003's business)."""
    root = make_project(tmp_path, {
        "k.py": """\
            from jax.experimental import pallas as pl

            def _kern(x_ref, o_ref):
                v = o_ref[...]
                o_ref[...] = v[0] + v[1]

            def launch(x):
                return pl.pallas_call(
                    _kern,
                    out_shape=x,
                    input_output_aliases={0: 0},
                )(x)
            """,
    })
    assert by_rule(run_lint(root), "GL002") == []


def test_gl002_follows_conditional_alias_and_helper_calls(tmp_path):
    """The partition.py shape: the ref aliases through an IfExp into a
    local name, and separately flows BY NAME into an in-package helper
    whose read then fires."""
    root = make_project(tmp_path, {
        "k.py": """\
            from jax.experimental import pallas as pl

            def _read(src, o_ref):
                return src[0]

            def _kern(x_ref, o_ref, flag):
                src = x_ref if flag else o_ref
                tile = src[...]
                o_ref[...] = tile + _read(x_ref, o_ref)

            def launch(x, flag):
                return pl.pallas_call(
                    _kern,
                    out_shape=x,
                    input_output_aliases={0: 0},
                )(x, flag)
            """,
    })
    assert idents(run_lint(root), "GL002") == {
        "_kern:_kern:src",  # IfExp alias read in the kernel body
        "_kern:_read:src",  # exact-Name arg flow into the helper
    }


# ===================================================================== GL003
def test_gl003_flags_host_sync_through_the_call_graph(tmp_path):
    """float()/.item()/np.asarray/jax.device_get on tracer-flowing values,
    including one hop into an in-package helper."""
    root = make_project(tmp_path, {
        "app.py": """\
            import jax
            import numpy as np

            def _helper(v):
                s = v + 1
                return float(s)

            @instrumented_jit
            def entry(x):
                y = x * 2
                host = np.asarray(x)
                pulled = jax.device_get(x)
                return _helper(x) + y.item()
            """,
    })
    assert idents(run_lint(root), "GL003") == {
        "_helper:float:s",
        "entry:numpy.asarray:x",
        "entry:jax.device_get:",
        "entry:.item:y",
    }


def test_gl003_silent_on_static_argnames_and_unreachable_code(tmp_path):
    """static_argnames values never become tracers (the split_scan_pallas
    idiom: float(l1) on a static hyper-parameter is fine), and host code
    the call graph cannot reach from an entry is out of scope."""
    root = make_project(tmp_path, {
        "app.py": """\
            import functools

            @functools.partial(instrumented_jit, static_argnames=("n",))
            def entry(x, n):
                return x * int(n)

            def cold_path(v):
                return float(v)
            """,
    })
    assert by_rule(run_lint(root), "GL003") == []


# ===================================================================== GL004
def test_gl004_weak_float_closure_vs_pinned_and_int(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            import jax.numpy as jnp

            EPS = 1e-6
            SCALE = 2.5
            N_TILES = 4

            @instrumented_jit
            def bad(x):
                return x + EPS

            @instrumented_jit
            def good(x):
                return x * jnp.asarray(SCALE, jnp.float32) + N_TILES

            def unjitted(x):
                return x + EPS
            """,
    })
    assert idents(run_lint(root), "GL004") == {"bad:EPS"}


# ===================================================================== GL005
def test_gl005_block_and_contract_checks(tmp_path):
    """One enclosing function per defect so each ident isolates one check:
    lane alignment, dtype-aware sublane, index_map arity and rank,
    out_specs/out_shape count and rank."""
    root = make_project(tmp_path, {
        "k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            LANES = 128

            def bad_lane(x):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    out_shape=jax.ShapeDtypeStruct((8, 64), jnp.float32),
                    out_specs=pl.BlockSpec((8, 64), lambda i: (0, 0)),
                )(x)

            def bad_sublane_bf16(x):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    out_shape=jax.ShapeDtypeStruct((64, LANES), jnp.bfloat16),
                    out_specs=pl.BlockSpec((8, LANES), lambda i: (0, 0)),
                )(x)

            def bad_arity(x):
                return pl.pallas_call(
                    kern,
                    grid=(2, 2),
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                )(x)

            def bad_rank(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0,)),
                )(x)

            def bad_count(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    out_shape=[jax.ShapeDtypeStruct((8, 128), jnp.float32)],
                    out_specs=[
                        pl.BlockSpec((8, 128), lambda i: (0, 0)),
                        pl.BlockSpec((8, 128), lambda i: (0, 0)),
                    ],
                )(x)

            def bad_out_rank(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    out_shape=jax.ShapeDtypeStruct((2, 8, 128), jnp.float32),
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                )(x)
            """,
    })
    assert idents(run_lint(root), "GL005") == {
        "bad_lane:out_specs[0]:lane",
        "bad_sublane_bf16:out_specs[0]:sublane",
        "bad_arity:out_specs[0]:arity",
        "bad_rank:out_specs[0]:rank",
        "bad_count:out_specs:count",
        "bad_out_rank:out_specs[0]:out_rank",
    }


def test_gl005_silent_on_aligned_smem_and_unresolvable_dims(tmp_path):
    """Aligned VMEM blocks pass; 1-row blocks are allowed; SMEM specs are
    exempt from tiling; dims the linter cannot resolve are skipped, never
    guessed."""
    root = make_project(tmp_path, {
        "k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            LANES = 128

            def clean(x, n):
                return pl.pallas_call(
                    kern,
                    grid=(n, 2),
                    in_specs=[
                        pl.BlockSpec((1, 8, LANES), lambda i, j: (i, 0, 0)),
                        pl.BlockSpec((n, LANES), lambda i, j: (0, j)),
                        pl.BlockSpec(memory_space=pltpu.SMEM),
                        pl.BlockSpec((1, LANES), lambda i, j: (0, j)),
                    ],
                    out_shape=jax.ShapeDtypeStruct((16, 128), jnp.bfloat16),
                    out_specs=pl.BlockSpec((16, LANES), lambda i, j: (i, j)),
                )(x)
            """,
    })
    assert by_rule(run_lint(root), "GL005") == []


# ===================================================================== GL006
def test_gl006_orphan_config_field(tmp_path):
    root = make_project(tmp_path, {
        "config.py": """\
            class Config:
                used: int = 1
                getattr_used: int = 2
                orphan: int = 3
                raw: dict = None
            """,
        "consumer.py": """\
            def f(cfg, obj):
                return cfg.used + getattr(obj, "getattr_used", 0)
            """,
    })
    assert idents(run_lint(root), "GL006") == {"orphan"}


# ===================================================================== GL007
def test_gl007_flags_raw_lax_collective(tmp_path):
    """Raw jax.lax collectives outside obs/collectives.py break the
    every-site-is-measured invariant; the timed wrappers stay silent."""
    root = make_project(tmp_path, {
        "app.py": """\
            import jax

            def leaf_stats(x):
                s = jax.lax.psum(x, "data")
                return jax.lax.pmax(s, "data")

            def measured(x):
                return timed_psum(x, "data", site="s")
            """,
    })
    assert idents(run_lint(root), "GL007") == {
        "leaf_stats:raw-psum:1",
        "leaf_stats:raw-pmax:1",
    }


def test_gl007_flags_one_sided_collective_behind_plain_if(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            def grow(x, use_fast):
                if use_fast:
                    x = timed_psum(x, "data", site="s")
                return x
            """,
    })
    assert idents(run_lint(root), "GL007") == {"grow:if:use_fast"}


def test_gl007_silent_on_axis_derived_and_static_derived_guards(tmp_path):
    """The grower's guard idioms: a gate computed from the axis-name
    family (use_par) or from a jit entry's static argument (mode) is
    trace-static — every replica traces the same side."""
    root = make_project(tmp_path, {
        "app.py": """\
            import functools

            def grow(x, axis_name):
                use_par = axis_name is not None
                if use_par:
                    x = timed_psum(x, axis_name, site="s")
                return x

            @functools.partial(instrumented_jit, static_argnames=("mode",))
            def entry(x, mode):
                fast = mode == "seg"
                if fast:
                    x = timed_psum(x, "data", site="s")
                return x
            """,
    })
    assert by_rule(run_lint(root), "GL007") == []


def test_gl007_early_return_sibling_is_congruent(tmp_path):
    """`if skip: return psum(...)` followed by an unconditional psum is
    congruent (both paths post one psum); an early RAISE guard creates no
    sibling at all (validation raises must not fire)."""
    root = make_project(tmp_path, {
        "app.py": """\
            def grow(x, skip):
                if skip:
                    return timed_psum(x, "data", site="a")
                return timed_psum(x, "data", site="b")

            def checked(x, n):
                if n < 0:
                    raise ValueError("bad")
                return timed_psum(x, "data", site="s")
            """,
    })
    assert by_rule(run_lint(root), "GL007") == []


def test_gl007_lax_cond_branch_congruence(tmp_path):
    """A collective in only one lax.cond branch deadlocks for real (the
    predicate is traced); congruent branches stay silent, and a switch
    with an unresolvable branch list is skipped, never guessed."""
    root = make_project(tmp_path, {
        "app.py": """\
            from jax import lax

            def bad_gate(pred, x, axis_name):
                def _with(x):
                    return timed_psum(x, axis_name, site="s")
                def _without(x):
                    return x
                return lax.cond(pred, _with, _without, x)

            def good_gate(pred, x, axis_name):
                def _left(x):
                    return timed_psum(x, axis_name, site="l")
                def _right(x):
                    return timed_psum(x * 2, axis_name, site="r")
                return lax.cond(pred, _left, _right, x)

            def unresolvable(idx, branches, x):
                return lax.switch(idx, branches, x)
            """,
    })
    assert idents(run_lint(root), "GL007") == {"bad_gate:cond:1"}


# ===================================================================== GL008
def test_gl008_flags_mixed_axis_sources_in_one_jitted_region(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            @instrumented_jit
            def entry(x, axis_name):
                x = timed_psum(x, axis_name, site="a")
                return timed_pmax(x, "data", site="b")
            """,
    })
    assert idents(run_lint(root), "GL008") == {"entry:axis-sources"}


def test_gl008_flags_collective_reachable_with_none_axis(tmp_path):
    """An Optional axis source with no `is not None` dominator fires; the
    guarded spelling (the grower idiom) stays silent."""
    root = make_project(tmp_path, {
        "app.py": """\
            def unguarded(x, axis_name=None):
                return timed_psum(x, axis_name, site="s")

            def guarded(x, axis_name=None):
                if axis_name is not None:
                    x = timed_psum(x, axis_name, site="s")
                return x
            """,
    })
    assert idents(run_lint(root), "GL008") == {"unguarded:none-psum:1"}


def test_gl008_silent_on_single_source_through_helpers(tmp_path):
    """Axis-argument specialization: a helper whose site uses its own
    axis_name parameter takes the CALLER's source, so plumbing one literal
    through a helper is still one source."""
    root = make_project(tmp_path, {
        "app.py": """\
            def helper(x, axis_name):
                return timed_psum(x, axis_name, site="h")

            @instrumented_jit
            def entry(x):
                x = helper(x, "data")
                return timed_pmax(x, "data", site="b")
            """,
    })
    assert by_rule(run_lint(root), "GL008") == []


def test_gl008_mesh_axis_table_literals_are_one_source(tmp_path):
    """The two-axis world: literals drawn from the project's
    MESH_AXIS_NAMES table (parallel/mesh.py) are ONE consistent source —
    a 2-D grow path psums histograms over 'data' and elects the winner
    over 'feature' inside the same jitted region."""
    root = make_project(tmp_path, {
        "parallel/mesh.py": """\
            MESH_AXIS_NAMES = ("data", "feature")
            """,
        "app.py": """\
            @instrumented_jit
            def entry(x):
                x = timed_psum(x, "data", site="hist")
                return timed_psum(x, "feature", site="elect")
            """,
    })
    assert by_rule(run_lint(root), "GL008") == []


def test_gl008_mesh_table_does_not_launder_foreign_sources(tmp_path):
    """The collapse merges ONLY table literals: a typo'd axis next to a
    table literal, or a table literal mixed with the params plumbing,
    are still two sources."""
    root = make_project(tmp_path, {
        "parallel/mesh.py": """\
            MESH_AXIS_NAMES = ("data", "feature")
            """,
        "app.py": """\
            @instrumented_jit
            def typo(x):
                x = timed_psum(x, "data", site="hist")
                return timed_psum(x, "mdata", site="elect")

            @instrumented_jit
            def mixed(x, axis_name):
                x = timed_psum(x, axis_name, site="hist")
                return timed_psum(x, "feature", site="elect")
            """,
    })
    assert idents(run_lint(root), "GL008") == {
        "typo:axis-sources", "mixed:axis-sources",
    }


# ===================================================================== GL009
def test_gl009_flags_nonstatic_scalar_params(tmp_path):
    """Scalar-annotated params outside static_argnames retrace per value;
    declared statics, asarray-pinned scalars, unannotated params, and the
    bare-Tuple idiom (a tuple OF ARRAYS, grow_tree's forced) are exempt."""
    root = make_project(tmp_path, {
        "app.py": """\
            import functools
            from typing import Optional, Tuple

            import jax.numpy as jnp

            @functools.partial(instrumented_jit, static_argnames=("n",))
            def entry(x, n: int, lr: float, shape: Tuple[int, int],
                      forced: Optional[Tuple] = None, rng=None):
                return x * lr

            @instrumented_jit
            def pinned(x, lr: float):
                r = jnp.asarray(lr, jnp.float32)
                return x * r
            """,
    })
    assert idents(run_lint(root), "GL009") == {"entry:lr", "entry:shape"}


def test_gl009_flags_unordered_callbacks(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            from jax.experimental import io_callback

            def measured(x, shape, fn):
                t0 = io_callback(fn, shape, x)
                t1 = io_callback(fn, shape, x, ordered=True)
                return t0 + t1
            """,
    })
    assert idents(run_lint(root), "GL009") == {"measured:io_callback:1"}


# ===================================================================== GL010
def test_gl010_flags_process_index_gating_a_collective(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            import jax

            def sync(x):
                pidx = jax.process_index()
                if pidx == 0:
                    return process_allgather(x)
                return x
            """,
    })
    assert idents(run_lint(root), "GL010") == {"sync:pidx == 0"}


def test_gl010_silent_on_uniform_gates_and_seeded_rng(tmp_path):
    """process_count() is identical on every host, a seeded rng draws the
    same stream everywhere, and a divergent store onto self must not mark
    every later self.* gate divergent."""
    root = make_project(tmp_path, {
        "app.py": """\
            import time

            import jax
            import numpy as np

            def agg(x):
                if jax.process_count() <= 1:
                    return x
                return process_allgather(x)

            def bag(x):
                r = np.random.default_rng(0).random()
                if r > 0.5:
                    return timed_psum(x, "data", site="s")
                return timed_psum(x * 2, "data", site="s")

            class Booster:
                def setup(self, x):
                    self._t0 = time.monotonic()
                    if self._mesh is not None:
                        return process_allgather(x)
                    return x
            """,
    })
    assert by_rule(run_lint(root), "GL010") == []


def test_gl010_follows_divergent_taint_through_calls(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            import jax

            def _gather_if(flag, x):
                if flag:
                    return process_allgather(x)
                return x

            def sync(x):
                rank = jax.process_index()
                lead = rank == 0
                return _gather_if(lead, x)
            """,
    })
    assert idents(run_lint(root), "GL010") == {"_gather_if:flag"}


# ================================================= taint forwarding (GL003)
def test_gl003_taint_follows_star_args_forwarding(tmp_path):
    """Tainted values survive positional overflow into *args AND a *args
    re-splat into an in-package callee."""
    root = make_project(tmp_path, {
        "app.py": """\
            def _inner(a, b):
                return float(b)

            def _fwd(*args):
                return _inner(*args)

            @instrumented_jit
            def entry(x):
                return _fwd(0, x)
            """,
    })
    assert "_inner:float:b" in idents(run_lint(root), "GL003")


def test_gl003_taint_follows_kwargs_forwarding(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            def _inner(a=0, b=0):
                return b.item()

            def _fwd(**kw):
                return _inner(**kw)

            @instrumented_jit
            def entry(x):
                return _fwd(b=x)
            """,
    })
    assert "_inner:.item:b" in idents(run_lint(root), "GL003")


def test_gl003_forwarding_untainted_values_stays_silent(tmp_path):
    """Forwarding only STATIC values through *args/**kwargs must not
    invent taint (the over-approximation is per forwarded value, not per
    forwarding site)."""
    root = make_project(tmp_path, {
        "app.py": """\
            import functools

            def _inner(a, b):
                return float(b)

            def _fwd(*args, **kw):
                return _inner(*args, **kw)

            @functools.partial(instrumented_jit, static_argnames=("n", "m"))
            def entry(x, n, m):
                return x + _fwd(n, b=m)
            """,
    })
    assert by_rule(run_lint(root), "GL003") == []


# ================================================================ suppression
@pytest.mark.parametrize(
    "comment,fires",
    [
        ("# graftlint: disable=GL001", False),
        ("# graftlint: disable=GL002,GL001", False),
        ("# graftlint: disable", False),  # bare disable: all rules
        ("# graftlint: disable=GL005", True),  # wrong code: still fires
        ("", True),
    ],
)
def test_suppression_comment_is_rule_scoped(tmp_path, comment, fires):
    root = make_project(tmp_path, {
        "app.py": f"""\
            import jax

            def build(fn):
                return jax.jit(fn)  {comment}
            """,
    })
    assert bool(by_rule(run_lint(root), "GL001")) is fires


# =================================================================== baseline
def test_baseline_round_trip_and_stale_detection(tmp_path):
    files = {
        "app.py": """\
            import jax

            def build(fn):
                return jax.jit(fn)
            """,
    }
    root = make_project(tmp_path, files)
    bp = tmp_path / "baseline.json"

    # 1) no baseline: the finding is NEW and the gate fails
    first = run_lint(root)
    assert not first.ok and len(first.new) == 1

    # 2) absorb into the baseline: same tree is now clean
    write_baseline(bp, first.findings)
    entries = load_baseline(bp)
    assert [e["ident"] for e in entries] == ["build"]
    assert all("justification" in e for e in entries)
    absorbed = run_lint(root, baseline=bp)
    assert absorbed.ok and not absorbed.new and not absorbed.stale

    # 3) fix the code: the baseline entry goes STALE and fails the run —
    #    a baseline only shrinks through review, never silently
    (root / "app.py").write_text("def build(fn):\n    return fn\n")
    fixed = run_lint(root, baseline=bp)
    assert not fixed.ok
    assert not fixed.new
    assert [e["ident"] for e in fixed.stale] == ["build"]


def test_baseline_rejects_entries_without_justification(tmp_path):
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({
        "version": 1,
        "entries": [{"rule": "GL001", "path": "x.py", "ident": "f"}],
    }))
    with pytest.raises(SystemExit):
        load_baseline(bp)


# =========================================================== mutation battery
# Each mutation re-seeds a known bug shape into a copy of the REAL module
# and must be caught by exactly the intended rule — if a refactor of the
# analyzer stops catching one of these, the battery fails before the bug
# class can silently return.
_PARTITION = PKG / "ops" / "pallas" / "partition.py"
_ALIAS_LINE = "src = seg_in if read_via_input else seg_out"


def _partition_copy(tmp_path, mutate):
    src = _PARTITION.read_text()
    assert _ALIAS_LINE in src  # the mutation target still exists
    if mutate:
        # strip the inline suppression, then re-seed the PR-3 bug: read the
        # INPUT side of the alias unconditionally
        src = re.sub(r"#\s*graftlint:[^\n]*", "", src)
        src = src.replace(_ALIAS_LINE, "src = seg_in")
    return make_project(tmp_path, {"ops/pallas/partition.py": src})


def test_mutation_seeded_aliased_read_is_caught(tmp_path):
    """Re-introducing the aliasing bug into a copy of the REAL partition
    kernel fires GL002 through the _seg_partition_kernel ->
    _partition_window -> read_aliased_tile chain."""
    res = run_lint(_partition_copy(tmp_path, mutate=True))
    assert "_seg_partition_kernel:read_aliased_tile:src" in idents(
        res, "GL002"
    )
    assert not res.ok


def test_mutation_control_pristine_copy_is_clean(tmp_path):
    """The unmutated copy carries the reviewed inline suppression for the
    test-only read_via_input knob and produces no GL002."""
    res = run_lint(_partition_copy(tmp_path, mutate=False))
    assert by_rule(res, "GL002") == []


_GROWER = PKG / "ops" / "grower.py"
_QUANTIZE = PKG / "ops" / "quantize.py"
_SPMD_RULES = ("GL007", "GL008", "GL009", "GL010")

# a one-sided collective inside a lax.cond branch — the deadlock shape
# GL007 exists for (the guard family can't save you: pred is traced)
_MUTANT_GATE = '''

def _mutant_gate(pred, x, axis_name):
    def _with(x):
        return timed_psum(x, axis_name, site="mutant")

    def _without(x):
        return x

    return lax.cond(pred, _with, _without, x)
'''

# the voting-aggregation psum — unique anchor string in grow_tree
_AXIS_SITE = 'totals, p.axis_name, site="counts",'


def _grower_copy(tmp_path, mutate=None):
    src = _GROWER.read_text()
    if mutate == "cond":
        src += _MUTANT_GATE
    elif mutate == "axis":
        assert _AXIS_SITE in src  # the mutation target still exists
        src = src.replace(_AXIS_SITE, 'totals, "mdata", site="counts",', 1)
    return make_project(tmp_path, {"ops/grower.py": src})


def _spmd_idents(res):
    return {rule: idents(res, rule) for rule in _SPMD_RULES}


def test_mutation_control_pristine_grower_copy_is_clean(tmp_path):
    """grow_tree's real guard idioms (axis-derived use_par-style gates,
    static-argnames-derived use_seg/use_gather gates, congruent
    early-return psums) all stay silent on the unmutated copy."""
    res = run_lint(_grower_copy(tmp_path))
    assert _spmd_idents(res) == {rule: set() for rule in _SPMD_RULES}


def test_mutation_one_sided_cond_psum_is_caught_by_gl007_only(tmp_path):
    res = run_lint(_grower_copy(tmp_path, mutate="cond"))
    found = _spmd_idents(res)
    assert found["GL007"] == {"_mutant_gate:cond:1"}
    assert found["GL008"] == found["GL009"] == found["GL010"] == set()


def test_mutation_axis_literal_mismatch_is_caught_by_gl008_only(tmp_path):
    """Replacing one site's p.axis_name with a literal "mdata" puts two
    axis-name sources inside the grow_tree jitted region."""
    res = run_lint(_grower_copy(tmp_path, mutate="axis"))
    found = _spmd_idents(res)
    assert found["GL008"] == {"grow_tree:axis-sources"}
    assert found["GL007"] == found["GL009"] == found["GL010"] == set()


def _quantize_copy(tmp_path, mutate):
    src = _QUANTIZE.read_text()
    if mutate:
        assert '"num_leaves",' in src  # the mutation target still exists
        src = re.sub(r'\n\s*"num_leaves",', "", src, count=1)
    return make_project(tmp_path, {"ops/quantize.py": src})


def test_mutation_dropped_static_argname_is_caught_by_gl009_only(tmp_path):
    """Dropping num_leaves from renew_leaf_values' static_argnames makes a
    scalar-annotated param retrace per value — the exact hole the PR-7
    retrace accounting paid for at runtime."""
    clean = run_lint(_quantize_copy(tmp_path, mutate=False))
    assert _spmd_idents(clean) == {rule: set() for rule in _SPMD_RULES}

    res = run_lint(_quantize_copy(tmp_path, mutate=True))
    found = _spmd_idents(res)
    assert found["GL009"] == {"renew_leaf_values:num_leaves"}
    assert found["GL007"] == found["GL008"] == found["GL010"] == set()


# ================================================================== the gate
def test_real_tree_clean_against_committed_baseline():
    """THE gate: the shipped package has zero unbaselined findings and zero
    stale baseline entries, within the 6 s budget (tightened from 10 s when
    the SPMD rules landed — the shared SpmdIndex keeps GL007–GL010 to one
    walk, so the full ten-rule run must stay inside a dev-loop budget).
    The budget is the CLI's own CPU accounting in a FRESH interpreter —
    how the tool is actually invoked (run_tests.sh, the dev loop) — not a
    wall clock inside this long-lived pytest process, where hundreds of
    earlier tests leave the allocator fragmented enough to roughly double
    the cost of the pointer-chasing ast walk."""
    res = run_lint(PKG, baseline=REPO / "lint_baseline.json")
    assert res.ok, (
        "new findings:\n"
        + "\n".join(f.render() for f in res.new)
        + "\nstale baseline entries:\n"
        + "\n".join(str(e) for e in res.stale)
    )

    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint",
         "--baseline", str(REPO / "lint_baseline.json"), "--json"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    cpu = json.loads(proc.stdout)["cpu_s"]
    assert cpu < 6.0, f"lint took {cpu:.1f}s CPU (budget: 6s)"


def test_cli_exit_codes():
    """``python -m lightgbm_tpu.lint`` is the CI entry point: exit 0
    against the committed baseline, exit 1 when the baseline is empty (all
    21 accepted exceptions become NEW findings); ``--json`` reports a
    wall-time entry per shipped AST rule (IR rules are timed only under
    ``--ir`` — see tests/test_lint_ir.py)."""
    ok = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint",
         "--baseline", str(REPO / "lint_baseline.json")],
        cwd=REPO, capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr

    empty = REPO / "tests" / "golden"  # any dir; baseline file must not exist
    bad = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint",
         "--baseline", str(empty / "no_such_baseline.json"), "--json"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert bad.returncode == 1
    payload = json.loads(bad.stdout)
    assert payload["new"], "expected the baselined findings to surface"
    assert set(payload["rule_timings_s"]) == set(RULES) - IR_RULE_CODES
    assert all(t >= 0 for t in payload["rule_timings_s"].values())


def test_cli_changed_only_smoke():
    """--changed-only exits 0 whether or not anything is modified: a dirty
    checkout reports only changed-file findings against the baseline and a
    clean one short-circuits before analysis."""
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint", "--changed-only",
         "--baseline", str(REPO / "lint_baseline.json")],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_rule_table_is_complete():
    """Every rule has a summary and an actionable autofix hint, and the
    fifteen shipped codes (ten AST + five IR) are exactly the documented
    set."""
    assert set(RULES) == {f"GL{i:03d}" for i in range(1, 16)}
    assert IR_RULE_CODES == {f"GL{i:03d}" for i in range(11, 16)}
    for code, (summary, hint) in RULES.items():
        assert summary and hint, code
