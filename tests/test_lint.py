"""graftlint (lightgbm_tpu.lint) — the static-analysis CI gate.

Contracts under test:
  * every rule (GL001..GL006) FIRES on a seeded positive fixture and stays
    SILENT on the matching negative — the linter is pure ast, so fixtures
    are throwaway source trees written to tmp_path and never imported;
  * per-line ``# graftlint: disable[=CODES]`` suppression works and is
    rule-scoped;
  * the baseline round-trips: new findings fail the run, ``write_baseline``
    absorbs them, entries that stop firing go STALE and fail the run (a
    baseline may only shrink through review);
  * mutation test: re-seeding the PR-3/PR-6 aliased-ref-read bug into a
    copy of ops/pallas/partition.py is caught by GL002 through the real
    kernel -> _partition_window -> read_aliased_tile call chain;
  * the real tree is CLEAN against the committed lint_baseline.json and a
    full run fits the 10 s budget (it is a hard gate in tools/run_tests.sh).
"""

import json
import re
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from lightgbm_tpu.lint import (
    RULES,
    load_baseline,
    run_lint,
    write_baseline,
)

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "lightgbm_tpu"


def make_project(tmp_path, files, name="fixpkg"):
    """Write a throwaway package tree and return its root."""
    root = tmp_path / name
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return root


def by_rule(result, rule):
    return [f for f in result.findings if f.rule == rule]


def idents(result, rule):
    return {f.ident for f in by_rule(result, rule)}


# ===================================================================== GL001
def test_gl001_flags_every_bare_jit_reference(tmp_path):
    """Call form, assignment form, and decorator form all fire; the ident
    is the enclosing function, so the baseline key survives line churn."""
    root = make_project(tmp_path, {
        "app.py": """\
            import jax

            j = jax.pmap

            def build(fn):
                return jax.jit(fn)

            @jax.jit
            def decorated(x):
                return x
            """,
    })
    res = run_lint(root)
    assert idents(res, "GL001") == {"<module>", "build", "decorated"}
    assert not res.ok  # no baseline: every finding is new -> gate fails


def test_gl001_silent_on_instrumented_jit_and_inside_wrapper_module(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            from .obs.jit import instrumented_jit

            @instrumented_jit
            def f(x):
                return x
            """,
        "obs/__init__.py": "",
        "obs/jit.py": """\
            import jax

            def instrumented_jit(fun, **kw):
                return jax.jit(fun, **kw)
            """,
    })
    assert by_rule(run_lint(root), "GL001") == []


# ===================================================================== GL002
_GL002_KERNEL = """\
    from jax.experimental import pallas as pl

    def _kern(x_ref, o_ref):
        o_ref[...] = {read} + 1.0

    def launch(x):
        return pl.pallas_call(
            _kern,
            out_shape=x,
            input_output_aliases={{0: 0}},
        )(x)
    """


def test_gl002_flags_direct_read_of_input_aliased_ref(tmp_path):
    root = make_project(
        tmp_path, {"k.py": _GL002_KERNEL.format(read="x_ref[...]")}
    )
    assert idents(run_lint(root), "GL002") == {"_kern:_kern:x_ref"}


def test_gl002_silent_on_output_ref_and_derived_values(tmp_path):
    """Reading the OUTPUT alias is the fix; subscripting a value that came
    FROM the ref is not a ref read (value taint is GL003's business)."""
    root = make_project(tmp_path, {
        "k.py": """\
            from jax.experimental import pallas as pl

            def _kern(x_ref, o_ref):
                v = o_ref[...]
                o_ref[...] = v[0] + v[1]

            def launch(x):
                return pl.pallas_call(
                    _kern,
                    out_shape=x,
                    input_output_aliases={0: 0},
                )(x)
            """,
    })
    assert by_rule(run_lint(root), "GL002") == []


def test_gl002_follows_conditional_alias_and_helper_calls(tmp_path):
    """The partition.py shape: the ref aliases through an IfExp into a
    local name, and separately flows BY NAME into an in-package helper
    whose read then fires."""
    root = make_project(tmp_path, {
        "k.py": """\
            from jax.experimental import pallas as pl

            def _read(src, o_ref):
                return src[0]

            def _kern(x_ref, o_ref, flag):
                src = x_ref if flag else o_ref
                tile = src[...]
                o_ref[...] = tile + _read(x_ref, o_ref)

            def launch(x, flag):
                return pl.pallas_call(
                    _kern,
                    out_shape=x,
                    input_output_aliases={0: 0},
                )(x, flag)
            """,
    })
    assert idents(run_lint(root), "GL002") == {
        "_kern:_kern:src",  # IfExp alias read in the kernel body
        "_kern:_read:src",  # exact-Name arg flow into the helper
    }


# ===================================================================== GL003
def test_gl003_flags_host_sync_through_the_call_graph(tmp_path):
    """float()/.item()/np.asarray/jax.device_get on tracer-flowing values,
    including one hop into an in-package helper."""
    root = make_project(tmp_path, {
        "app.py": """\
            import jax
            import numpy as np

            def _helper(v):
                s = v + 1
                return float(s)

            @instrumented_jit
            def entry(x):
                y = x * 2
                host = np.asarray(x)
                pulled = jax.device_get(x)
                return _helper(x) + y.item()
            """,
    })
    assert idents(run_lint(root), "GL003") == {
        "_helper:float:s",
        "entry:numpy.asarray:x",
        "entry:jax.device_get:",
        "entry:.item:y",
    }


def test_gl003_silent_on_static_argnames_and_unreachable_code(tmp_path):
    """static_argnames values never become tracers (the split_scan_pallas
    idiom: float(l1) on a static hyper-parameter is fine), and host code
    the call graph cannot reach from an entry is out of scope."""
    root = make_project(tmp_path, {
        "app.py": """\
            import functools

            @functools.partial(instrumented_jit, static_argnames=("n",))
            def entry(x, n):
                return x * int(n)

            def cold_path(v):
                return float(v)
            """,
    })
    assert by_rule(run_lint(root), "GL003") == []


# ===================================================================== GL004
def test_gl004_weak_float_closure_vs_pinned_and_int(tmp_path):
    root = make_project(tmp_path, {
        "app.py": """\
            import jax.numpy as jnp

            EPS = 1e-6
            SCALE = 2.5
            N_TILES = 4

            @instrumented_jit
            def bad(x):
                return x + EPS

            @instrumented_jit
            def good(x):
                return x * jnp.asarray(SCALE, jnp.float32) + N_TILES

            def unjitted(x):
                return x + EPS
            """,
    })
    assert idents(run_lint(root), "GL004") == {"bad:EPS"}


# ===================================================================== GL005
def test_gl005_block_and_contract_checks(tmp_path):
    """One enclosing function per defect so each ident isolates one check:
    lane alignment, dtype-aware sublane, index_map arity and rank,
    out_specs/out_shape count and rank."""
    root = make_project(tmp_path, {
        "k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            LANES = 128

            def bad_lane(x):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    out_shape=jax.ShapeDtypeStruct((8, 64), jnp.float32),
                    out_specs=pl.BlockSpec((8, 64), lambda i: (0, 0)),
                )(x)

            def bad_sublane_bf16(x):
                return pl.pallas_call(
                    kern,
                    grid=(4,),
                    out_shape=jax.ShapeDtypeStruct((64, LANES), jnp.bfloat16),
                    out_specs=pl.BlockSpec((8, LANES), lambda i: (0, 0)),
                )(x)

            def bad_arity(x):
                return pl.pallas_call(
                    kern,
                    grid=(2, 2),
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                )(x)

            def bad_rank(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0,)),
                )(x)

            def bad_count(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    out_shape=[jax.ShapeDtypeStruct((8, 128), jnp.float32)],
                    out_specs=[
                        pl.BlockSpec((8, 128), lambda i: (0, 0)),
                        pl.BlockSpec((8, 128), lambda i: (0, 0)),
                    ],
                )(x)

            def bad_out_rank(x):
                return pl.pallas_call(
                    kern,
                    grid=(2,),
                    out_shape=jax.ShapeDtypeStruct((2, 8, 128), jnp.float32),
                    out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                )(x)
            """,
    })
    assert idents(run_lint(root), "GL005") == {
        "bad_lane:out_specs[0]:lane",
        "bad_sublane_bf16:out_specs[0]:sublane",
        "bad_arity:out_specs[0]:arity",
        "bad_rank:out_specs[0]:rank",
        "bad_count:out_specs:count",
        "bad_out_rank:out_specs[0]:out_rank",
    }


def test_gl005_silent_on_aligned_smem_and_unresolvable_dims(tmp_path):
    """Aligned VMEM blocks pass; 1-row blocks are allowed; SMEM specs are
    exempt from tiling; dims the linter cannot resolve are skipped, never
    guessed."""
    root = make_project(tmp_path, {
        "k.py": """\
            import jax
            import jax.numpy as jnp
            from jax.experimental import pallas as pl

            LANES = 128

            def clean(x, n):
                return pl.pallas_call(
                    kern,
                    grid=(n, 2),
                    in_specs=[
                        pl.BlockSpec((1, 8, LANES), lambda i, j: (i, 0, 0)),
                        pl.BlockSpec((n, LANES), lambda i, j: (0, j)),
                        pl.BlockSpec(memory_space=pltpu.SMEM),
                        pl.BlockSpec((1, LANES), lambda i, j: (0, j)),
                    ],
                    out_shape=jax.ShapeDtypeStruct((16, 128), jnp.bfloat16),
                    out_specs=pl.BlockSpec((16, LANES), lambda i, j: (i, j)),
                )(x)
            """,
    })
    assert by_rule(run_lint(root), "GL005") == []


# ===================================================================== GL006
def test_gl006_orphan_config_field(tmp_path):
    root = make_project(tmp_path, {
        "config.py": """\
            class Config:
                used: int = 1
                getattr_used: int = 2
                orphan: int = 3
                raw: dict = None
            """,
        "consumer.py": """\
            def f(cfg, obj):
                return cfg.used + getattr(obj, "getattr_used", 0)
            """,
    })
    assert idents(run_lint(root), "GL006") == {"orphan"}


# ================================================================ suppression
@pytest.mark.parametrize(
    "comment,fires",
    [
        ("# graftlint: disable=GL001", False),
        ("# graftlint: disable=GL002,GL001", False),
        ("# graftlint: disable", False),  # bare disable: all rules
        ("# graftlint: disable=GL005", True),  # wrong code: still fires
        ("", True),
    ],
)
def test_suppression_comment_is_rule_scoped(tmp_path, comment, fires):
    root = make_project(tmp_path, {
        "app.py": f"""\
            import jax

            def build(fn):
                return jax.jit(fn)  {comment}
            """,
    })
    assert bool(by_rule(run_lint(root), "GL001")) is fires


# =================================================================== baseline
def test_baseline_round_trip_and_stale_detection(tmp_path):
    files = {
        "app.py": """\
            import jax

            def build(fn):
                return jax.jit(fn)
            """,
    }
    root = make_project(tmp_path, files)
    bp = tmp_path / "baseline.json"

    # 1) no baseline: the finding is NEW and the gate fails
    first = run_lint(root)
    assert not first.ok and len(first.new) == 1

    # 2) absorb into the baseline: same tree is now clean
    write_baseline(bp, first.findings)
    entries = load_baseline(bp)
    assert [e["ident"] for e in entries] == ["build"]
    assert all("justification" in e for e in entries)
    absorbed = run_lint(root, baseline=bp)
    assert absorbed.ok and not absorbed.new and not absorbed.stale

    # 3) fix the code: the baseline entry goes STALE and fails the run —
    #    a baseline only shrinks through review, never silently
    (root / "app.py").write_text("def build(fn):\n    return fn\n")
    fixed = run_lint(root, baseline=bp)
    assert not fixed.ok
    assert not fixed.new
    assert [e["ident"] for e in fixed.stale] == ["build"]


def test_baseline_rejects_entries_without_justification(tmp_path):
    bp = tmp_path / "b.json"
    bp.write_text(json.dumps({
        "version": 1,
        "entries": [{"rule": "GL001", "path": "x.py", "ident": "f"}],
    }))
    with pytest.raises(SystemExit):
        load_baseline(bp)


# ============================================================== mutation test
_PARTITION = PKG / "ops" / "pallas" / "partition.py"
_ALIAS_LINE = "src = seg_in if read_via_input else seg_out"


def _partition_copy(tmp_path, mutate):
    src = _PARTITION.read_text()
    assert _ALIAS_LINE in src  # the mutation target still exists
    if mutate:
        # strip the inline suppression, then re-seed the PR-3 bug: read the
        # INPUT side of the alias unconditionally
        src = re.sub(r"#\s*graftlint:[^\n]*", "", src)
        src = src.replace(_ALIAS_LINE, "src = seg_in")
    return make_project(tmp_path, {"ops/pallas/partition.py": src})


def test_mutation_seeded_aliased_read_is_caught(tmp_path):
    """Re-introducing the aliasing bug into a copy of the REAL partition
    kernel fires GL002 through the _seg_partition_kernel ->
    _partition_window -> read_aliased_tile chain."""
    res = run_lint(_partition_copy(tmp_path, mutate=True))
    assert "_seg_partition_kernel:read_aliased_tile:src" in idents(
        res, "GL002"
    )
    assert not res.ok


def test_mutation_control_pristine_copy_is_clean(tmp_path):
    """The unmutated copy carries the reviewed inline suppression for the
    test-only read_via_input knob and produces no GL002."""
    res = run_lint(_partition_copy(tmp_path, mutate=False))
    assert by_rule(res, "GL002") == []


# ================================================================== the gate
def test_real_tree_clean_against_committed_baseline():
    """THE gate: the shipped package has zero unbaselined findings and zero
    stale baseline entries, within the 10 s budget."""
    t0 = time.monotonic()
    res = run_lint(PKG, baseline=REPO / "lint_baseline.json")
    elapsed = time.monotonic() - t0
    assert res.ok, (
        "new findings:\n"
        + "\n".join(f.render() for f in res.new)
        + "\nstale baseline entries:\n"
        + "\n".join(str(e) for e in res.stale)
    )
    assert elapsed < 10.0, f"lint took {elapsed:.1f}s (budget: 10s)"


def test_cli_exit_codes():
    """``python -m lightgbm_tpu.lint`` is the CI entry point: exit 0
    against the committed baseline, exit 1 when the baseline is empty (all
    19 accepted exceptions become NEW findings)."""
    ok = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint",
         "--baseline", str(REPO / "lint_baseline.json")],
        cwd=REPO, capture_output=True, text=True,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr

    empty = REPO / "tests" / "golden"  # any dir; baseline file must not exist
    bad = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.lint",
         "--baseline", str(empty / "no_such_baseline.json"), "--json"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert bad.returncode == 1
    payload = json.loads(bad.stdout)
    assert payload["new"], "expected the baselined findings to surface"


def test_rule_table_is_complete():
    """Every rule has a summary and an actionable autofix hint, and the six
    shipped codes are exactly the documented set."""
    assert set(RULES) == {f"GL00{i}" for i in range(1, 7)}
    for code, (summary, hint) in RULES.items():
        assert summary and hint, code
